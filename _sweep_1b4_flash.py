"""Interleaved op-level flash block sweep at the 1.36B attention shape
(b=1, h=16, s=8192, d=128, causal, fwd+bwd train grad).  Only interleaved
same-process A/Bs resolve <15% differences through this tunnel
(BASELINE.md method note)."""
import functools, json, time
import jax, jax.numpy as jnp
from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

B, H, S, D = 1, 16, 8192, 128
rng = jax.random.key(0)
q = jax.random.normal(jax.random.fold_in(rng, 0), (B, S, H, D), jnp.bfloat16)
k = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, H, D), jnp.bfloat16)
v = jax.random.normal(jax.random.fold_in(rng, 2), (B, S, H, D), jnp.bfloat16)

CONFIGS = [(1024, 1024), (512, 1024), (1024, 512), (512, 512), (256, 1024)]

def make_step(bq, bk):
    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    return g

steps = {}
for bq, bk in CONFIGS:
    try:
        g = make_step(bq, bk)
        out = g(q, k, v)
        float(jnp.sum(out[0].astype(jnp.float32)))
        steps[(bq, bk)] = g
    except Exception as e:
        print(json.dumps({"cfg": [bq, bk], "ok": False,
                          "err": str(e)[:120]}), flush=True)

REPS, ROUNDS = 10, 6
times = {c: [] for c in steps}
for r in range(ROUNDS):
    for c, g in steps.items():
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = g(q, k, v)
        float(jnp.sum(out[0].astype(jnp.float32)))
        times[c].append((time.perf_counter() - t0) / REPS)
for c, ts in times.items():
    ts.sort()
    print(json.dumps({"cfg": list(c), "ok": True,
                      "min_ms": round(ts[0] * 1e3, 2),
                      "med_ms": round(ts[len(ts)//2] * 1e3, 2)}), flush=True)
