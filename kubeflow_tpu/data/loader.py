"""Sharded batch loading for SPMD training.

``ShardedLoader`` wraps any per-host numpy-batch iterator and emits global
``jax.Array``s laid out for the mesh: the host supplies its *local* slice
(``global_batch / process_count`` rows), and
``jax.make_array_from_process_local_data`` stitches the global view without
cross-host gathers.  Double-buffering (one batch prefetched on a thread)
overlaps host input with device compute — the TPU analogue of the
reference images' in-notebook ``torch.utils.data.DataLoader`` workers.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding


class ShardedLoader:
    """Iterate (host-local numpy pytrees) → (global sharded jax.Array pytrees).

    ``sharding``: a NamedSharding (or pytree of them matching the batch
    structure) describing the *global* batch layout.  ``prefetch`` > 0 runs
    the host iterator on a background thread.
    """

    def __init__(
        self,
        local_batches: Iterator[Any],
        sharding: Any,
        *,
        prefetch: int = 2,
    ):
        self._it = iter(local_batches)
        self._sharding = sharding
        self._prefetch = prefetch
        # Per-generation feeder state: each __iter__ captures its OWN stop
        # event and queue, so an abandoned older generator's cleanup can
        # never kill or starve the live one.
        self._thread: Optional[threading.Thread] = None
        self._thread_stop: Optional[threading.Event] = None
        self._done = object()

    def _assemble(self, local: Any) -> Any:
        def one(x, sh):
            if isinstance(x, jax.Array):
                return x
            return jax.make_array_from_process_local_data(sh, np.asarray(x))

        if isinstance(self._sharding, NamedSharding):
            return jax.tree.map(lambda x: one(x, self._sharding), local)
        return jax.tree.map(one, local, self._sharding)

    def _feeder(self, q: queue.Queue, stop: threading.Event):
        def put(item) -> bool:
            # Bounded put that gives up when this generation's consumer
            # stopped (a consumer that breaks out of its loop must not leave
            # this thread blocked holding assembled device batches).
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        sentinel = self._done
        try:
            for item in self._it:
                if stop.is_set():
                    return
                if not put(self._assemble(item)):
                    return
        except BaseException as exc:  # propagated to the consumer, not lost
            sentinel = exc
        put(sentinel)

    def __iter__(self):
        if self._prefetch <= 0:
            for item in self._it:
                yield self._assemble(item)
            return
        if self._thread is not None:
            # A previous iteration was abandoned: release and retire its
            # feeder before re-arming, so two feeders never share self._it.
            self._thread_stop.set()
            self._thread.join()
        q = queue.Queue(maxsize=self._prefetch)
        stop = threading.Event()
        self._thread_stop = stop
        self._thread = threading.Thread(
            target=self._feeder, args=(q, stop), daemon=True
        )
        self._thread.start()
        try:
            while True:
                item = q.get()
                if item is self._done:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # Consumer finished or broke out early: release THIS generation's
            # feeder and drop its prefetched batches so device memory frees.
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass


def _host_batch_size(global_batch: int) -> int:
    n_proc = jax.process_count()
    if global_batch % n_proc:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n_proc} hosts"
        )
    return global_batch // n_proc


def synthetic_lm_batches(
    *,
    global_batch: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    steps: Optional[int] = None,
    start: int = 0,
) -> Iterator[np.ndarray]:
    """Host-local random token batches [local_batch, seq_len] (int32).

    Step-indexed: batch ``i`` depends only on ``(seed, host, i)``, so a
    resumed run (``start = restored step``) replays the exact stream a
    non-interrupted run would have seen — checkpoint/resume is bit-exact
    including the data order.  Both ``start`` and ``steps`` are absolute
    step indices (the stream yields batches ``start .. steps-1``, matching
    the train loop's optimizer step numbering), NOT a count from ``start``.
    """
    local = _host_batch_size(global_batch)
    host = jax.process_index()
    i = start
    while steps is None or i < steps:
        rng = np.random.default_rng((seed, host, i))
        yield rng.integers(0, vocab_size, (local, seq_len), dtype=np.int32)
        i += 1


def synthetic_lm_documents(
    *,
    vocab_size: int,
    seed: int = 0,
    min_len: int = 8,
    max_len: int = 256,
    docs: Optional[int] = None,
) -> Iterator[np.ndarray]:
    """Variable-length random token documents — the input side of the
    packing pipeline (kubeflow_tpu.data.packing.packed_lm_batches)."""
    rng = np.random.default_rng((seed, jax.process_index()))
    i = 0
    while docs is None or i < docs:
        n = int(rng.integers(min_len, max_len + 1))
        yield rng.integers(1, vocab_size, n, dtype=np.int32)
        i += 1


def synthetic_image_batches(
    *,
    global_batch: int,
    image_size: int = 224,
    num_classes: int = 1000,
    channels: int = 3,
    seed: int = 0,
    steps: Optional[int] = None,
    start: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Host-local (images [l,H,W,C] f32, labels [l] int32) batches.
    Step-indexed like synthetic_lm_batches (``start``/``steps`` are absolute
    step indices) — exact stream under resume."""
    local = _host_batch_size(global_batch)
    host = jax.process_index()
    i = start
    while steps is None or i < steps:
        rng = np.random.default_rng((seed, host, i))
        images = rng.standard_normal((local, image_size, image_size, channels)).astype(
            np.float32
        )
        labels = rng.integers(0, num_classes, (local,), dtype=np.int32)
        yield images, labels
        i += 1
