"""Host-side data pipeline: per-host loading → global sharded arrays.

The reference platform has no data loading (SURVEY.md §2.13 — data is the
user's notebook's problem).  Here the multi-host story is first-class: each
host produces only its local shard of the global batch and
``jax.make_array_from_process_local_data`` assembles the global array with
the training sharding — no host ever materializes the full batch, and no
device-device traffic is spent re-sharding input.
"""

from kubeflow_tpu.data.loader import (
    ShardedLoader,
    synthetic_image_batches,
    synthetic_lm_batches,
)

__all__ = ["ShardedLoader", "synthetic_lm_batches", "synthetic_image_batches"]
