"""Sequence packing: variable-length documents → fixed [rows, seq_len]
token matrices with segment ids.

Padding is wasted MXU time: a batch of raw documents padded to seq_len
spends FLOPs and HBM on pad tokens.  Packing places several documents in
one row and tells attention where the boundaries are via segment ids
(ops/attention.py masks cross-segment pairs; kubeflow_tpu.train's LM step
masks cross-boundary and pad targets out of the loss).

The bin-packing itself (best-fit decreasing) runs in the native C++
engine when available (native/packer.cc via platform/native.py) with a
pure-Python mirror — the same native-with-fallback pattern as the
platform's JSON-patch and workqueue hot paths.

Conventions: segment ids start at 1 per row; 0 marks padding slots.
"""
from __future__ import annotations

import bisect
from typing import List, Sequence, Tuple

import numpy as np


def pack_documents(
    lengths: Sequence[int], row_len: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Assign documents to rows, best-fit decreasing.

    Returns ``(row_assignment, row_offset, n_rows)``: for document i,
    ``row_assignment[i]`` is its row and ``row_offset[i]`` its first slot.
    Raises ValueError if any length is < 1 or > row_len.
    """
    from kubeflow_tpu.platform import native

    lengths = np.asarray(lengths, dtype=np.int64)
    result = native.native_pack(lengths, row_len)
    if result is not None:
        return result
    return _pack_python(lengths, row_len)


def _pack_python(lengths: np.ndarray, row_len: int):
    """Pure-Python best-fit decreasing (parity-tested vs the C++ engine)."""
    if any(l < 1 or l > row_len for l in lengths):
        raise ValueError(f"invalid document lengths for row_len={row_len}")
    order = sorted(range(len(lengths)), key=lambda i: -int(lengths[i]))
    assignment = np.empty(len(lengths), dtype=np.int64)
    offset = np.empty(len(lengths), dtype=np.int64)
    open_rows: List[Tuple[int, int]] = []  # sorted (remaining, row_id)
    used: List[int] = []
    for i in order:
        length = int(lengths[i])
        j = bisect.bisect_left(open_rows, (length, -1))
        if j == len(open_rows):
            row = len(used)
            used.append(0)
        else:
            row = open_rows[j][1]
            del open_rows[j]
        assignment[i] = row
        offset[i] = used[row]
        used[row] += length
        rem = row_len - used[row]
        if rem > 0:
            bisect.insort(open_rows, (rem, row))
    return assignment, offset, len(used)


def pack_tokens(
    docs: Sequence[np.ndarray], row_len: int, *, pad_id: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack token documents into ``(tokens, segment_ids)`` matrices of
    shape [n_rows, row_len].  Documents longer than row_len raise (split
    upstream — silently truncating training data hides bugs)."""
    lengths = [len(d) for d in docs]
    assignment, offset, n_rows = pack_documents(lengths, row_len)
    tokens, segments, carry = _materialize_rows(
        docs, lengths, assignment, offset, n_rows, row_len, pad_id
    )
    assert not carry  # keep_rows == n_rows: everything materializes
    return tokens, segments


def _materialize_rows(
    window, lengths, assignment, offset, keep_rows: int, seq_len: int,
    pad_id: int,
):
    """Token/segment matrices for rows < keep_rows, plus the documents that
    landed in later rows (carried into the next window — never dropped)."""
    tokens = np.full((keep_rows, seq_len), pad_id, dtype=np.int32)
    segments = np.zeros((keep_rows, seq_len), dtype=np.int32)
    seg_counter = np.zeros(keep_rows, dtype=np.int32)
    carry: List[np.ndarray] = []
    for i, doc in enumerate(window):
        r, o = int(assignment[i]), int(offset[i])
        if r >= keep_rows:
            carry.append(doc)
            continue
        seg_counter[r] += 1
        tokens[r, o:o + lengths[i]] = np.asarray(doc, dtype=np.int32)
        segments[r, o:o + lengths[i]] = seg_counter[r]
    return tokens, segments, carry


def packed_lm_batches(
    docs, *, batch_rows: int, seq_len: int, pad_id: int = 0,
    drop_remainder: bool = True,
):
    """Generator: stream of token documents → (tokens, segment_ids) batches
    of shape [batch_rows, seq_len].  Packs over a rolling window; documents
    the packer places beyond batch_rows carry into the next window — no
    document is ever silently dropped (documents longer than seq_len
    raise)."""
    window: List[np.ndarray] = []
    total = 0
    for doc in docs:
        doc = np.asarray(doc)
        window.append(doc)
        total += len(doc)
        if total < batch_rows * seq_len:
            continue
        lengths = [len(d) for d in window]
        assignment, offset, n_rows = pack_documents(lengths, seq_len)
        # total >= batch_rows*seq_len and each row holds <= seq_len tokens,
        # so n_rows >= batch_rows here — always enough rows to emit.
        tokens, segments, carry = _materialize_rows(
            window, lengths, assignment, offset, batch_rows, seq_len, pad_id
        )
        yield tokens, segments
        window = carry
        total = sum(len(d) for d in carry)
    while window and not drop_remainder:
        lengths = [len(d) for d in window]
        assignment, offset, n_rows = pack_documents(lengths, seq_len)
        tokens, segments, carry = _materialize_rows(
            window, lengths, assignment, offset, batch_rows, seq_len, pad_id
        )
        yield tokens, segments
        window = carry
