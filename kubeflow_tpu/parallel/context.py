"""Global mesh context: lets leaf ops (ring attention) find the active mesh
without threading it through every model signature."""
from __future__ import annotations

import contextlib
from typing import Optional

from jax.sharding import Mesh

_CURRENT_MESH: Optional[Mesh] = None


def set_global_mesh(mesh: Optional[Mesh]) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH


@contextlib.contextmanager
def global_mesh(mesh: Mesh):
    prev = get_global_mesh()
    set_global_mesh(mesh)
    try:
        yield mesh
    finally:
        set_global_mesh(prev)
