"""The TPU worker env contract, single-sourced.

Both halves of the repo speak this vocabulary: the platform controllers
(notebook.py, tpujob.py) INJECT these variables into worker pods, and the
compute side (parallel/dist.py) DISCOVERS them to join the
``jax.distributed`` barrier.  Before this module the strings were
free-floating in both places and could silently drift — a renamed variable
on either side would strand every multi-host worker at the rendezvous with
no error.  Now the controller builds its env list from these constants and
``dist.worker_env`` parses through ``worker_env_from`` below; the
round-trip is pinned by tests/ctrlplane/test_tpujob_controller.py.

Deliberately dependency-free (no jax import): the platform half imports
this from reconcile hot paths where pulling in jax would cost seconds of
import time and hundreds of MB of RSS.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# -- per-slice libtpu ICI bootstrap (the GKE TPU-webhook contract) -----------
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
ENV_TPU_CHIPS_PER_HOST = "TPU_CHIPS_PER_HOST"
ENV_TPU_HOSTS_PER_SLICE = "TPU_HOSTS_PER_SLICE"

# -- cross-slice (DCN) identity: GKE multislice / MEGASCALE parity -----------
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
ENV_MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"

# -- platform → trainer plumbing (kubeflow-tpu specific, not libtpu) ---------
# Checkpoint directory a TPUJob's gang resumes from (train/run.py reads it
# as the --checkpoint-dir default; docs/jobs.md "checkpoint-resume").
ENV_KFT_CHECKPOINT_DIR = "KFT_CHECKPOINT_DIR"
# Elastic capacity (docs/jobs.md "Queueing, priority, and preemption"):
# MEGASCALE_NUM_SLICES always carries the GRANTED gang width, so
# ``dist.process_grid`` remaps the dcn(dp) axis for free when a preempted
# or shrunk gang resumes at fewer slices.  KFT_SPEC_SLICES rides along
# with the job's FULL spec.tpu.slices so the trainer can tell it is
# running shrunk (``dist.elastic_slices``) and log/export it.
ENV_KFT_SPEC_SLICES = "KFT_SPEC_SLICES"

# The jax.distributed rendezvous port — what dist.initialize dials and the
# controllers' headless coordinator Services expose.  Lives here (not in
# dist.py, which re-exports it) because the controllers cannot afford the
# jax import; one constant on both sides of the wire.
DEFAULT_COORDINATOR_PORT = 8476

# StatefulSet pods carry their ordinal in this label; the downward-API
# fieldRef below turns it into TPU_WORKER_ID.
_POD_INDEX_FIELD = "metadata.labels['apps.kubernetes.io/pod-index']"


def tpu_bootstrap_env(*, topology: str, accelerator: str, chips: int,
                      chips_per_host: int, num_hosts: int,
                      hostnames: str) -> List[dict]:
    """The per-slice libtpu ICI bootstrap block a controller injects into
    every worker of one slice — k8s EnvVar-shaped dicts, value formats
    exactly what ``worker_env_from`` reads back (e.g. the
    ``<accelerator>-<chips>`` accelerator-type string).  Shared by the
    notebook and TPUJob reconcilers so the formatting cannot drift between
    workloads."""
    return [
        {"name": ENV_TPU_WORKER_ID, "valueFrom": {"fieldRef": {
            "fieldPath": _POD_INDEX_FIELD}}},
        {"name": ENV_TPU_WORKER_HOSTNAMES, "value": hostnames},
        {"name": ENV_TPU_TOPOLOGY, "value": topology},
        {"name": ENV_TPU_ACCELERATOR_TYPE,
         "value": f"{accelerator}-{chips}"},
        {"name": ENV_TPU_CHIPS_PER_HOST, "value": str(chips_per_host)},
        {"name": ENV_TPU_HOSTS_PER_SLICE, "value": str(num_hosts)},
    ]


def megascale_env(slice_id: int, num_slices: int,
                  coordinator_address: str) -> List[dict]:
    """The cross-slice env block a controller injects into every worker of
    slice ``slice_id`` — k8s EnvVar-shaped dicts, values stringified the
    way ``worker_env_from`` will read them back."""
    return [
        {"name": ENV_MEGASCALE_SLICE_ID, "value": str(slice_id)},
        {"name": ENV_MEGASCALE_NUM_SLICES, "value": str(num_slices)},
        {"name": ENV_MEGASCALE_COORDINATOR_ADDRESS,
         "value": coordinator_address},
    ]


def elastic_env(spec_slices: int) -> List[dict]:
    """The elastic-capacity marker a controller injects next to the
    MEGASCALE block: the job's full DECLARED width (the granted width is
    already MEGASCALE_NUM_SLICES via ``megascale_env``), so a shrunk
    gang's trainer knows ``allocated < spec`` (discovery:
    ``worker_env_from``'s ``spec_slices`` / ``dist.elastic_slices``)."""
    return [{"name": ENV_KFT_SPEC_SLICES, "value": str(spec_slices)}]


def worker_env_from(environ: Dict[str, str]) -> Dict[str, Optional[str]]:
    """Parse the injected contract out of an environ mapping — the ONE
    discovery implementation (dist.worker_env binds it to os.environ)."""
    return {
        "worker_id": environ.get(ENV_TPU_WORKER_ID),
        "hostnames": environ.get(ENV_TPU_WORKER_HOSTNAMES),
        "topology": environ.get(ENV_TPU_TOPOLOGY),
        "accelerator": environ.get(ENV_TPU_ACCELERATOR_TYPE),
        "hosts_per_slice": environ.get(ENV_TPU_HOSTS_PER_SLICE),
        "num_slices": environ.get(ENV_MEGASCALE_NUM_SLICES),
        "slice_id": environ.get(ENV_MEGASCALE_SLICE_ID),
        "coordinator": environ.get(ENV_MEGASCALE_COORDINATOR_ADDRESS),
        "spec_slices": environ.get(ENV_KFT_SPEC_SLICES),
    }
