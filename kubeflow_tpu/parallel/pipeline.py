"""Pipeline parallelism: GPipe-style microbatching over the ``pp`` mesh axis.

The reference platform has no parallelism code at all (SURVEY.md §2.13); this
is part of the first-class distributed story of the TPU rebuild.  Design is
the TPU-idiomatic one (scaling-book "pipelining" chapter), not a scheduler
translation: every stage runs the *same* jitted program under ``shard_map``;
activations hop to the next stage with ``lax.ppermute``; the schedule is a
``lax.scan`` over ``n_micro + n_stages - 1`` ticks, so the whole pipeline is
one XLA computation with static shapes — no host round-trips between ticks.

Semantics: ``pipeline_apply(fn, stage_params, x)`` ≡ feeding ``x`` through
``fn(params_0) ∘ fn(params_1) … ∘ fn(params_{P-1})`` applied stage 0 → P-1,
microbatched along the leading axis.  Stage parameters live sharded on
``pp`` (each device holds only its stage's slice — pipeline parallelism *is*
that placement); inputs/outputs are replicated across ``pp`` and may be
sharded on the other axes as usual.

The bubble is the standard GPipe one: P-1 idle ticks out of M + P - 1, so
choose n_micro ≫ n_stages.  Backward runs by differentiating through the
scan — XLA re-plays the schedule in reverse, which is exactly the GPipe
backward (activations rematerialized per ``jax.checkpoint`` policy if the
caller wraps ``fn``).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(params, x, *, fn, axis_name, n_micro):
    """Per-device body under shard_map.

    params: this stage's param pytree (leading ``pp`` axis already split
    away by shard_map, leaving one stage's params).
    x: full input batch [B, ...] (replicated over pp), microbatched here.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    micro = batch // n_micro
    # [M, micro, ...]
    xs = x.reshape((n_micro, micro) + x.shape[1:])

    state = jnp.zeros_like(xs[0])  # activation currently held by this stage
    outputs = jnp.zeros_like(xs)

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 injects microbatch t (zeros once the stream is drained —
        # those results are never read back).
        inject = jax.lax.dynamic_index_in_dim(
            xs, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False
        )
        x_in = jnp.where(stage == 0, inject, state)
        y = fn(params, x_in)
        # Last stage records its result for microbatch t - (P-1); every
        # other (stage, tick) combination writes the previous value back
        # (a no-op), keeping the scan branch-free.
        out_idx = t - (n_stages - 1)
        idx = jnp.maximum(out_idx, 0)
        prev = jax.lax.dynamic_index_in_dim(outputs, idx, axis=0, keepdims=False)
        val = jnp.where((stage == n_stages - 1) & (out_idx >= 0), y, prev)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, val, idx, 0)
        # Hand the activation to the next stage.
        state = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(
        tick, (state, outputs), jnp.arange(n_micro + n_stages - 1)
    )
    # Results live on the last stage; broadcast them to every stage so the
    # output is replicated over pp (psum of one-hot contribution).
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    outputs = jax.lax.psum(outputs, axis_name)
    return outputs.reshape((batch,) + outputs.shape[2:])


def _pipeline_local_interleaved(
    chunks, x, *, fn, axis_name, n_micro, n_rounds
):
    """Per-device body for the circular (interleaved) schedule.

    chunks: this device's ``n_rounds`` stage chunks, leaves [v, ...] —
    local row r is GLOBAL stage ``r * P + d`` (round-robin placement), so
    an activation travels d=0..P-1 with r=0, wraps to d=0, travels again
    with r=1, and so on: v laps of the ring apply all v*P stages in order.

    Microbatch m enters device 0 at tick m; device d applies round r to it
    at tick ``m + d + r*P``.  With n_micro <= P no two activations ever
    collide at a device, so the schedule is closed-form and branch-free.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            f"per-device batch {batch} not divisible into {n_micro} microbatches"
        )
    micro = batch // n_micro
    xs = x.reshape((n_micro, micro) + x.shape[1:])

    state = jnp.zeros_like(xs[0])
    outputs = jnp.zeros_like(xs)
    # Full ring: the wrap edge (P-1 → 0) carries activations into their
    # next round.
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    # Last microbatch (m = n_micro-1) leaves the last device's last round
    # at tick m + v*P - 1; anything beyond that is pure drain waste.
    total_ticks = n_rounds * n_stages + n_micro - 1

    def tick(carry, t):
        state, outputs = carry
        rel = t - stage
        r = jnp.clip(rel // n_stages, 0, n_rounds - 1)
        params_r = jax.tree.map(
            lambda c: jax.lax.dynamic_index_in_dim(c, r, 0, keepdims=False),
            chunks,
        )
        inject = jax.lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False
        )
        x_in = jnp.where((stage == 0) & (t < n_micro), inject, state)
        y = fn(params_r, x_in)
        # Last device on its last round emits microbatch t - (v*P - 1).
        out_idx = t - (n_rounds * n_stages - 1)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, idx, axis=0, keepdims=False)
        done = (stage == n_stages - 1) & (out_idx >= 0) & (out_idx < n_micro)
        val = jnp.where(done, y, prev)
        outputs = jax.lax.dynamic_update_index_in_dim(outputs, val, idx, 0)
        state = jax.lax.ppermute(y, axis_name, ring)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(
        tick, (state, outputs), jnp.arange(total_ticks)
    )
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    outputs = jax.lax.psum(outputs, axis_name)
    return outputs.reshape((batch,) + outputs.shape[2:])


def pipeline_apply(
    fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_micro: int,
    interleave: int = 1,
    axis_name: str = "pp",
    param_specs: Any = None,
    x_spec: P = None,
):
    """Run ``x`` through the pipeline stages of ``fn`` over the ``pp`` axis.

    ``stage_params``: pytree whose leaves have a leading stage axis of size
    ``P * interleave`` — stage order is application order (stage 0 first).
    ``n_micro`` divides the *per-device* batch (the global batch divided by
    the data-axis extent), since microbatching happens after the data split.

    ``interleave=1`` is GPipe: device d holds stage d, bubble (P-1) thick
    ticks out of M + P - 1 — choose n_micro >> P.  ``interleave=v > 1`` is
    the circular schedule: device d holds the v stages {d, P+d, ..} and
    activations lap the ring v times, so the bubble is (P-1) ticks of a
    v×-smaller stage — the standard bubble reduction when microbatches are
    scarce (requires n_micro <= P; accumulate gradients across calls for
    bigger effective batches, train.steps.make_grad_accum_step).

    ``param_specs``: optional PartitionSpec pytree for the *per-stage* param
    leaves (the ``pp`` leading axis is prepended here); defaults to stage
    sharding only.  ``x_spec``: spec for inputs/outputs (no ``pp`` entry —
    they are replicated over pp); defaults to batch over (dp, fsdp).
    """
    n_stages = mesh.shape[axis_name]
    total_stages = n_stages * interleave
    if interleave < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    if interleave > 1 and n_micro > n_stages:
        raise ValueError(
            f"interleaved schedule needs n_micro <= pp ({n_stages}), got "
            f"{n_micro}; accumulate gradients across calls instead"
        )
    leaves = jax.tree.leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != total_stages:
            raise ValueError(
                f"stage_params leaves need leading axis {total_stages}, "
                f"got {leaf.shape}"
            )
    if x_spec is None:
        from kubeflow_tpu.parallel.sharding import data_axes

        x_spec = P(data_axes(mesh))
    if interleave > 1:
        # Round-robin placement: global stage r*P + d → device d, local row
        # r.  [v*P, ...] → [v, P, ...] → [P, v, ...].
        stage_params = jax.tree.map(
            lambda p: jnp.moveaxis(
                p.reshape((interleave, n_stages) + p.shape[1:]), 0, 1
            ),
            stage_params,
        )
    if param_specs is None:
        in_param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
    elif interleave > 1:
        # The reshape above inserted a local rounds axis after pp, so
        # per-stage spec entries shift by one: (pp, None[v], *spec).
        in_param_specs = jax.tree.map(
            lambda s: P(axis_name, None, *s), param_specs,
            is_leaf=lambda s: isinstance(s, P),
        )
    else:
        in_param_specs = jax.tree.map(
            lambda s: P(axis_name, *s), param_specs, is_leaf=lambda s: isinstance(s, P)
        )

    def body(params, x):
        # shard_map leaves the leading pp axis of size 1 on each device's
        # param block; strip it so fn sees this device's params.
        params = jax.tree.map(lambda p: p[0], params)
        if interleave == 1:
            return _pipeline_local(
                params, x, fn=fn, axis_name=axis_name, n_micro=n_micro
            )
        return _pipeline_local_interleaved(
            params, x, fn=fn, axis_name=axis_name, n_micro=n_micro,
            n_rounds=interleave,
        )

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(in_param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def stack_stage_params(per_stage: list) -> Any:
    """Stack a list of per-stage param pytrees into the [P, ...] layout."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)
