"""Partition rules: param-path patterns → PartitionSpec.

Models stay mesh-agnostic; sharding is decided here by matching the param
pytree paths (the flax module/param names) against ordered regex rules —
first match wins.  This is the pjit idiom: annotate, let XLA insert the
collectives (all-gather for fsdp params, psum for tp partials, reduce-scatter
for fsdp grads), never hand-write them in the model.

Llama layout (Megatron TP + FSDP on the orthogonal axis):

| param                     | shape                  | spec                        |
|---------------------------|------------------------|-----------------------------|
| embed.embedding           | (vocab, dim)           | P("tp", "fsdp")             |
| attn q/k/v_proj.kernel    | (dim, heads, head_dim) | P("fsdp", "tp", None)       |
| attn o_proj.kernel        | (heads, head_dim, dim) | P("tp", None, "fsdp")       |
| mlp gate/up_proj.kernel   | (dim, ffn)             | P("fsdp", "tp")             |
| mlp down_proj.kernel      | (ffn, dim)             | P("tp", "fsdp")             |
| norms' scale              | (dim,)                 | P(None)                     |
| lm_head.kernel            | (dim, vocab)           | P("fsdp", "tp")             |

Column-parallel qkv/gate/up followed by row-parallel o/down means the only
TP collective per block is one psum after o_proj and one after down_proj —
the textbook Megatron pattern, expressed purely through shardings.
"""
from __future__ import annotations

import re
from typing import Any, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Partition-invariant threefry — random streams must not depend on how
# GSPMD shards the operands (see models/generate.py for the serving-side
# rationale; here it keeps init/dropout streams stable across mesh
# shapes).  Idempotent with generate.py's identical update.
jax.config.update("jax_threefry_partitionable", True)

Rules = Sequence[Tuple[str, P]]


def llama_rules() -> Rules:
    return (
        (r".*embed.*embedding$", P("tp", "fsdp")),
        # MoE experts: batched (n_experts, ...) tensors sharded on ep; the
        # in/out feature axes keep the Megatron column/row split on fsdp/tp.
        (r".*(w_gate|w_up)$", P("ep", "fsdp", "tp")),
        (r".*w_down$", P("ep", "tp", "fsdp")),
        (r".*router.*kernel$", P("fsdp", None)),
        (r".*(q_proj|k_proj|v_proj).*kernel$", P("fsdp", "tp", None)),
        (r".*o_proj.*kernel$", P("tp", None, "fsdp")),
        (r".*(gate_proj|up_proj).*kernel$", P("fsdp", "tp")),
        (r".*down_proj.*kernel$", P("tp", "fsdp")),
        (r".*lm_head.*kernel$", P("fsdp", "tp")),
        (r".*", P()),  # norms, biases: replicated
    )


def vit_rules() -> Rules:
    return (
        (r".*(q_proj|k_proj|v_proj).*kernel$", P("fsdp", "tp", None)),
        (r".*o_proj.*kernel$", P("tp", None, "fsdp")),
        (r".*fc1.*kernel$", P("fsdp", "tp")),
        (r".*fc2.*kernel$", P("tp", "fsdp")),
        (r".*head.*kernel$", P("fsdp", "tp")),
        (r".*", P()),
    )


def t5_rules() -> Rules:
    return (
        (r".*embed.*embedding$", P("tp", "fsdp")),
        (r".*(q_proj|k_proj|v_proj).*kernel$", P("fsdp", "tp", None)),
        (r".*o_proj.*kernel$", P("tp", None, "fsdp")),
        (r".*(wi_0|wi_1).*kernel$", P("fsdp", "tp")),
        (r".*/wo/kernel$", P("tp", "fsdp")),  # paths join with "/"
        (r".*lm_head.*kernel$", P("fsdp", "tp")),
        # Per-head relative-bias tables follow the head (tp) split.
        (r".*rel_embedding$", P(None, "tp")),
        (r".*", P()),
    )


def bert_rules() -> Rules:
    return (
        (r".*(tok_embed|pos_embed).*embedding$", P("tp", "fsdp")),
        # Segment-type table has 2 rows in every config — vocab axis must
        # stay replicated or tp>2 meshes fail at placement.
        (r".*type_embed.*embedding$", P(None, "fsdp")),
        (r".*(q_proj|k_proj|v_proj).*kernel$", P("fsdp", "tp", None)),
        (r".*o_proj.*kernel$", P("tp", None, "fsdp")),
        (r".*fc1.*kernel$", P("fsdp", "tp")),
        (r".*fc2.*kernel$", P("tp", "fsdp")),
        (r".*(pooler|classifier).*kernel$", P("fsdp", None)),
        (r".*", P()),
    )


def resnet_rules() -> Rules:
    # Convs: shard output channels on tp, nothing else; batch-norm stats
    # replicated.  FSDP on convnets this small isn't worth the gathers.
    return (
        (r".*head.*kernel$", P("fsdp", "tp")),
        (r".*", P()),
    )


def rules_for_model(model) -> Rules:
    """Partition rules for a model-zoo instance, by family.

    Explicit registry rather than a regex guess: an unknown family must
    raise (a silent catch-all would replicate every weight — ``tp=8``
    would 'work' with zero parallelism)."""
    name = type(model).__name__
    table = {
        "Llama": llama_rules,
        "ViT": vit_rules,
        "ResNet": resnet_rules,
        "T5": t5_rules,
        "Bert": bert_rules,
    }
    if name not in table:
        raise ValueError(
            f"no partition rules registered for model family {name!r}; "
            f"known: {sorted(table)}"
        )
    return table[name]()


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for_path(path_string: str, rules: Rules) -> P:
    for pattern, spec in rules:
        if re.fullmatch(pattern, path_string):
            return spec
    return P()


def _clamp_spec(spec: P, ndim: int) -> P:
    """Trim/pad a spec to the array rank (rules are written for the common
    shapes; scalars and odd ranks degrade to replication on extra axes)."""
    parts = list(spec)[:ndim]
    parts += [None] * (ndim - len(parts))
    return P(*parts)


# Path marker of nn.scan-stacked layer params (models/llama.py
# LlamaConfig.scan_layers): leaves gain a leading layer axis, so the
# matched spec shifts right by one (layer axis replicated — it is the
# scan's sequential axis, never a mesh axis).
SCAN_MARKER = "layers_scan"


def spec_for_leaf(path_string: str, rules: Rules, ndim: int) -> P:
    spec = spec_for_path(path_string, rules)
    if SCAN_MARKER in path_string and len(spec) > 0:
        spec = P(None, *spec)
    return _clamp_spec(spec, ndim)


def tree_specs(tree: Any, rules: Rules) -> Any:
    """PartitionSpec pytree matching ``tree`` by path rules."""

    def one(path, leaf):
        return spec_for_leaf(_path_str(path), rules, getattr(leaf, "ndim", 0))

    return jax.tree_util.tree_map_with_path(one, tree)


def tree_shardings(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    specs = tree_specs(tree, rules)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """device_put a param pytree according to the rules."""
    return jax.device_put(params, tree_shardings(params, mesh, rules))


def data_axes(mesh: Mesh) -> tuple:
    """The mesh axes the batch dimension shards over: dp, fsdp, and ep (ep
    doubles as a data axis outside MoE layers).  Single source of truth —
    ring/ulysses/pipeline and batch_sharding all consult this."""
    return tuple(a for a in ("dp", "fsdp", "ep") if a in mesh.axis_names)


def constrain(x, spec: P):
    """``with_sharding_constraint`` against the ambient global mesh; no-op
    without one, so models stay mesh-agnostic (single-chip jit, CPU tests)."""
    from kubeflow_tpu.parallel.context import get_global_mesh

    mesh = get_global_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def replicate_for_use(x):
    """ZeRO-3 use-site gather: constrain a sharded param replicated where it
    is consumed, so XLA all-gathers the shards right before the consuming op
    instead of letting the param's at-rest split leak into the activation
    shardings.  No-op without an ambient mesh."""
    if getattr(x, "ndim", 0) == 0:
        return x
    return constrain(x, P(*([None] * x.ndim)))


def batch_sharding(mesh: Mesh, *, seq_axis: bool = False) -> NamedSharding:
    """Batch data over all data-parallel axes; optionally shard seq on sp."""
    if seq_axis:
        return NamedSharding(mesh, P(data_axes(mesh), "sp"))
    return NamedSharding(mesh, P(data_axes(mesh)))


def page_pool_shards(mesh: Mesh) -> int:
    """How many shards the paged-KV pool axis splits into on ``mesh`` —
    the product of the data-axis sizes (the tp/sp axes never split the
    pool: K/V heads already shard over tp inside each position)."""
    import math as _math

    return _math.prod(mesh.shape[a] for a in data_axes(mesh)) or 1


def page_pool_spec(mesh: Mesh, ndim: int) -> P:
    """Partition spec for one paged-KV cache leaf: shard the flat
    pool-position axis over the data axes, replicate everything else.

    Pool leaves are [pool_positions, kv_h, d] (ndim 3) or, under
    scan_layers, [layers, pool_positions, kv_h, d] (ndim 4) — the pool
    axis is always ``ndim - 3``.  models/paged.py rounds ``num_pages``
    up to a multiple of ``page_pool_shards`` so shard boundaries always
    align with page boundaries: a page never straddles two devices, and
    every page-table indirection resolves within one shard's rows."""
    spec = [None] * ndim
    spec[ndim - 3] = data_axes(mesh)
    return P(*spec)


def page_pool_sharding(mesh: Mesh, ndim: int = 3) -> NamedSharding:
    """``NamedSharding`` form of :func:`page_pool_spec` (rank-3 default:
    the in-module view layers.Attention._update_cache constrains)."""
    return NamedSharding(mesh, page_pool_spec(mesh, ndim))


def infer_state_shardings(state: Any, mesh: Mesh, rules: Rules) -> Any:
    """Shardings for a full TrainState: params and opt_state follow the param
    rules (optax states mirror the param tree), scalars replicate."""
    from kubeflow_tpu.train.steps import TrainState  # local import, no cycle

    assert isinstance(state, TrainState)

    def shard_like_params(tree):
        return tree_shardings(tree, mesh, rules)

    replicated = NamedSharding(mesh, P())

    def opt_sharding(leaf_path, leaf):
        # Optax state leaves that mirror a param keep its sharding; scalar
        # counters replicate.  Matching by shape: mirrors have ndim>0 and the
        # same path tail inside the state pytree.
        spec = spec_for_leaf(
            _path_str(leaf_path), rules, getattr(leaf, "ndim", 0)
        )
        return NamedSharding(mesh, spec)

    return TrainState(
        step=replicated,
        params=shard_like_params(state.params),
        opt_state=jax.tree_util.tree_map_with_path(opt_sharding, state.opt_state),
        batch_stats=(
            None
            if state.batch_stats is None
            else jax.tree.map(lambda _: replicated, state.batch_stats)
        ),
        tx=state.tx,
        apply_fn=state.apply_fn,
    )
