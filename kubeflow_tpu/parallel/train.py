"""Sharded train-step construction: pure step + mesh + rules → pjit'd step."""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.sharding import (
    Rules,
    batch_sharding,
    infer_state_shardings,
    shard_params,
    tree_shardings,
)
from kubeflow_tpu.train.steps import TrainState


def shard_train_state(state: TrainState, mesh: Mesh, rules: Rules) -> TrainState:
    """Place an (unsharded, host-built) TrainState onto the mesh."""
    shardings = infer_state_shardings(state, mesh, rules)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s) if hasattr(x, "shape") else x,
        state,
        shardings,
    )


def make_sharded_train_step(
    step: Callable,
    state: TrainState,
    mesh: Mesh,
    rules: Rules,
    *,
    shard_sequence: bool = False,
    donate_state: bool = True,
):
    """jit the step with explicit in/out shardings.

    ``state`` is only used for its pytree structure.  Batches are sharded
    [batch → (dp, fsdp, ep), seq → sp if shard_sequence].  XLA lowers the
    annotations to psum/all-gather/reduce-scatter/all-to-all over ICI.
    """
    state_sh = infer_state_shardings(state, mesh, rules)
    data_sh = batch_sharding(mesh, seq_axis=shard_sequence)
    repl = NamedSharding(mesh, P())

    def wrapped(state, batch):
        return step(state, batch)

    jit_kwargs: dict = dict(
        in_shardings=(state_sh, None),
        out_shardings=(state_sh, repl),
    )
    if donate_state:
        jit_kwargs["donate_argnums"] = (0,)
    return jax.jit(wrapped, **jit_kwargs), data_sh
