"""Multi-host bring-up inside notebook pods.

The platform side injects per-worker env into every pod of a multi-host
slice notebook (see kubeflow_tpu/platform/controllers/notebook.py and the
TPU PodDefaults): ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
``TPU_TOPOLOGY`` — the same contract GKE's TPU webhook uses.  This module is
the compute-side consumer: call ``initialize_from_env()`` first thing in a
multi-host notebook and every worker joins the jax.distributed barrier, after
which ``jax.devices()`` spans the whole slice and collectives ride ICI.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

DEFAULT_COORDINATOR_PORT = 8476


def worker_env() -> dict:
    return {
        "worker_id": os.environ.get("TPU_WORKER_ID"),
        "hostnames": os.environ.get("TPU_WORKER_HOSTNAMES"),
        "topology": os.environ.get("TPU_TOPOLOGY"),
        "accelerator": os.environ.get("TPU_ACCELERATOR_TYPE"),
    }


def initialize_from_env(*, coordinator_port: int = DEFAULT_COORDINATOR_PORT) -> bool:
    """Join the slice's jax.distributed cluster if this is a multi-host pod.

    Returns True if distributed init ran, False for single-host (no-op).
    Worker 0 (the StatefulSet's ``<name>-0`` pod, routed by the headless
    service the notebook controller creates) is the coordinator.
    """
    env = worker_env()
    if not env["hostnames"]:
        return False
    hosts = [h.strip() for h in env["hostnames"].split(",") if h.strip()]
    if len(hosts) <= 1:
        return False
    worker_id = int(env["worker_id"] or 0)
    coordinator = f"{hosts[0]}:{coordinator_port}"
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=len(hosts),
        process_id=worker_id,
    )
    return True
