"""Multi-host bring-up inside notebook pods.

The platform side injects per-worker env into every pod of a multi-host
slice notebook (see kubeflow_tpu/platform/controllers/notebook.py and the
TPU PodDefaults): ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
``TPU_TOPOLOGY`` — the same contract GKE's TPU webhook uses.  This module is
the compute-side consumer: call ``initialize_from_env()`` first thing in a
multi-host notebook and every worker joins the jax.distributed barrier, after
which ``jax.devices()`` spans the whole slice and collectives ride ICI.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

DEFAULT_COORDINATOR_PORT = 8476


def worker_env() -> dict:
    return {
        "worker_id": os.environ.get("TPU_WORKER_ID"),
        "hostnames": os.environ.get("TPU_WORKER_HOSTNAMES"),
        "topology": os.environ.get("TPU_TOPOLOGY"),
        "accelerator": os.environ.get("TPU_ACCELERATOR_TYPE"),
        "hosts_per_slice": os.environ.get("TPU_HOSTS_PER_SLICE"),
        "num_slices": os.environ.get("MEGASCALE_NUM_SLICES"),
        "slice_id": os.environ.get("MEGASCALE_SLICE_ID"),
        "coordinator": os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"),
    }


def num_slices() -> int:
    """Slices in this deployment (1 unless the platform spawned multislice)."""
    return int(worker_env()["num_slices"] or 1)


def slice_id() -> int:
    """Which slice this worker belongs to (MEGASCALE_SLICE_ID, per the
    notebook controller's one-StatefulSet-per-slice injection)."""
    return int(worker_env()["slice_id"] or 0)


def initialize_from_env(*, coordinator_port: int = DEFAULT_COORDINATOR_PORT) -> bool:
    """Join the deployment's jax.distributed cluster if this is a multi-host
    (or multislice) pod.

    Returns True if distributed init ran, False for single-host (no-op).
    ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` are *per-slice* (the libtpu
    ICI contract); the global process id folds in ``MEGASCALE_SLICE_ID`` so
    one barrier spans every slice, with worker 0 of slice 0 (the
    ``<name>-0`` pod routed by the headless service) as coordinator.
    """
    env = worker_env()
    if not env["hostnames"]:
        return False
    hosts = [h.strip() for h in env["hostnames"].split(",") if h.strip()]
    slices = num_slices()
    if len(hosts) * slices <= 1:
        return False
    worker_id = int(env["worker_id"] or 0)
    if slices > 1 and not env["coordinator"]:
        # hosts[0] is only the coordinator within ONE slice; without the
        # cross-slice address every slice would dial its own worker 0 and
        # all hosts would hang at the barrier — fail fast instead.
        raise RuntimeError(
            "MEGASCALE_NUM_SLICES > 1 but MEGASCALE_COORDINATOR_ADDRESS is "
            "unset; multislice needs the global coordinator address"
        )
    coordinator_host = env["coordinator"] or hosts[0]
    jax.distributed.initialize(
        coordinator_address=f"{coordinator_host}:{coordinator_port}",
        num_processes=len(hosts) * slices,
        process_id=slice_id() * len(hosts) + worker_id,
    )
    return True
