"""Multi-host bring-up inside notebook pods.

The platform side injects per-worker env into every pod of a multi-host
slice notebook (see kubeflow_tpu/platform/controllers/notebook.py and the
TPU PodDefaults): ``TPU_WORKER_ID``, ``TPU_WORKER_HOSTNAMES``,
``TPU_TOPOLOGY`` — the same contract GKE's TPU webhook uses.  This module is
the compute-side consumer: call ``initialize_from_env()`` first thing in a
multi-host notebook and every worker joins the jax.distributed barrier, after
which ``jax.devices()`` spans the whole slice and collectives ride ICI.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from kubeflow_tpu.parallel import envspec

# Single-sourced with the controllers' coordinator Service port.
DEFAULT_COORDINATOR_PORT = envspec.DEFAULT_COORDINATOR_PORT


def worker_env() -> dict:
    # The variable names live in parallel/envspec.py — the SAME constants
    # the platform controllers inject from, so discovery and injection
    # cannot drift (round-tripped in tests/ctrlplane/test_tpujob_controller).
    # Hands the WHOLE environ mapping to discovery — not a single knob
    # read, so the registry has nothing to record here.
    return envspec.worker_env_from(os.environ)  # kft: disable=R005 full-environ handoff


def num_slices() -> int:
    """Slices in this deployment (1 unless the platform spawned multislice)."""
    return int(worker_env()["num_slices"] or 1)


def slice_id() -> int:
    """Which slice this worker belongs to (MEGASCALE_SLICE_ID, per the
    notebook controller's one-StatefulSet-per-slice injection)."""
    return int(worker_env()["slice_id"] or 0)


def elastic_slices() -> tuple:
    """(allocated, declared) slice counts for elastic TPUJob gangs.

    The TPUJob queue admits a gang at ``allocated <= spec.tpu.slices``
    slices (down to ``minSlices``) and injects the GRANTED width as
    MEGASCALE_NUM_SLICES — so ``process_grid`` above already remaps the
    dcn(dp) axis to the shrunk world size and the same checkpoint resumes
    at fewer slices with zero trainer changes.  This helper exposes the
    declared width (KFT_SPEC_SLICES) next to it so a trainer can log or
    export "running shrunk at k/N"; outside a queue-admitted gang the two
    are equal."""
    env = worker_env()
    allocated = int(env["num_slices"] or 1)
    declared = int(env["spec_slices"] or allocated)
    return allocated, declared


def process_grid(
    env: Optional[dict] = None, *,
    coordinator_port: int = DEFAULT_COORDINATOR_PORT,
) -> Optional[tuple]:
    """Pure computation of the jax.distributed join parameters from the
    injected worker env: ``(coordinator_address, num_processes,
    process_id)``, or ``None`` for a single-host deployment.

    ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` are *per-slice* (the libtpu
    ICI contract); the global process id folds in ``MEGASCALE_SLICE_ID`` so
    one barrier spans every slice — slice-major, matching the slice-major
    device blocks ``make_hybrid_mesh`` assumes for its DCN axes.
    """
    env = env if env is not None else worker_env()
    if not env["hostnames"]:
        return None
    hosts = [h.strip() for h in env["hostnames"].split(",") if h.strip()]
    slices = int(env["num_slices"] or 1)
    if len(hosts) * slices <= 1:
        return None
    worker_id = int(env["worker_id"] or 0)
    if slices > 1 and not env["coordinator"]:
        # hosts[0] is only the coordinator within ONE slice; without the
        # cross-slice address every slice would dial its own worker 0 and
        # all hosts would hang at the barrier — fail fast instead.
        raise RuntimeError(
            "MEGASCALE_NUM_SLICES > 1 but MEGASCALE_COORDINATOR_ADDRESS is "
            "unset; multislice needs the global coordinator address"
        )
    coordinator_host = env["coordinator"] or hosts[0]
    sid = int(env["slice_id"] or 0)
    return (
        f"{coordinator_host}:{coordinator_port}",
        len(hosts) * slices,
        sid * len(hosts) + worker_id,
    )


def initialize_from_env(*, coordinator_port: int = DEFAULT_COORDINATOR_PORT) -> bool:
    """Join the deployment's jax.distributed cluster if this is a multi-host
    (or multislice) pod.

    Returns True if distributed init ran, False for single-host (no-op).
    See ``process_grid`` for the id layout.
    """
    grid = process_grid(coordinator_port=coordinator_port)
    if grid is None:
        return False
    coordinator_address, num_processes, process_id = grid
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
