"""Ulysses sequence parallelism: all-to-all head↔sequence re-sharding.

The second long-context strategy next to ``ring`` (SURVEY.md §5.7 — the
reference platform has no analogue; PAPERS.md: DeepSpeed-Ulysses).  Where
ring attention rotates K/V blocks around the ``sp`` ring (good when sequence
≫ heads), Ulysses re-shards with two all-to-alls: each device starts with a
sequence chunk of all heads, trades it for the *full* sequence of ``h/N``
heads, runs ordinary (flash) attention locally, and trades back.  On TPU the
all-to-all rides ICI and costs O(bytes/N) per device — cheaper than the ring
when heads divide evenly and the per-device sequence fits HBM.

    with mesh:
        out = ulysses_attention(q, k, v, mesh=mesh, causal=True)

Constraints: n_heads % sp == 0; n_kv_heads are repeated up to n_heads first
when they don't divide the axis (GQA).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _local_ulysses(q, k, v, *, axis_name, causal, scale, attn_fn):
    """Per-device body. q/k/v: [b, s_local, h, d] (full heads, seq chunk)."""
    from kubeflow_tpu.ops.attention import _repeat_kv

    axis_size = jax.lax.psum(1, axis_name)
    n_heads = q.shape[2]
    if k.shape[2] != n_heads and k.shape[2] % axis_size:
        # GQA with kv-head count not divisible by the axis: repeat to full.
        k = _repeat_kv(k, n_heads // k.shape[2])
        v = _repeat_kv(v, n_heads // v.shape[2])

    # seq-sharded/all-heads -> head-sharded/all-seq: split heads (axis 2)
    # across devices, concatenate sequence chunks (axis 1).
    def scatter_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    q_g, k_g, v_g = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attn_fn(q_g, k_g, v_g, causal=causal, scale=scale)
    return gather_heads(out.astype(q.dtype))


def _default_attn(q, k, v, *, causal, scale):
    """Attention on the local head group (full sequence): the Pallas flash
    kernel once the sequence passes its threshold — after the all-to-all
    each device holds the FULL sequence for its heads, exactly the shape
    the kernel is built for — else plain XLA."""
    from kubeflow_tpu.ops.attention import xla_attention
    from kubeflow_tpu.ops.pallas import flash_attention as fa

    if fa.supported(q, k, v) and fa.should_use(q):
        return fa.flash_attention(q, k, v, causal=causal, softmax_scale=scale)
    return xla_attention(q, k, v, causal=causal, softmax_scale=scale)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    attn_fn=None,
):
    """Exact attention with sequence sharded on ``axis_name`` via all-to-all.

    Same contract as ``ring_attention``: global-view BSHD in, same sharding
    out; composes with dp/fsdp/tp on the other mesh axes.  ``attn_fn`` lets
    callers swap the local kernel (e.g. the Pallas flash attention).
    """
    sp = mesh.shape[axis_name]
    if q.shape[2] % sp:
        raise ValueError(
            f"n_heads={q.shape[2]} must divide the {axis_name!r} axis ({sp})"
        )
    from kubeflow_tpu.parallel.sharding import data_axes

    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    spec = P(data_axes(mesh), axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _local_ulysses,
            axis_name=axis_name,
            causal=causal,
            scale=scale,
            attn_fn=attn_fn or _default_attn,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
