"""Device-mesh construction.

Axis convention (outer → inner, matching ICI locality on TPU slices):

* ``pp``   — pipeline parallelism (outermost: stage hand-offs are
  neighbor-to-neighbor once per microbatch, the most DCN-tolerant traffic,
  so this axis spans slice boundaries first)
* ``dp``   — pure data parallelism (gradients all-reduced)
* ``fsdp`` — data parallelism with sharded params/optimizer (ZeRO-3 style;
  XLA turns the annotations into all-gather / reduce-scatter)
* ``ep``   — expert parallelism (MoE experts sharded; token dispatch becomes
  an XLA all-to-all).  Doubles as a data axis in non-MoE layers.
* ``tp``   — tensor (Megatron) parallelism inside matmuls
* ``sp``   — sequence/context parallelism (ring attention)

Inner axes get the fastest ICI loops; ``tp`` and ``sp`` traffic is
latency-sensitive per-layer, while ``dp``/``fsdp``/``ep`` traffic amortizes
per step (grad sync, per-layer all-to-all), so the default order places
tp/sp innermost.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_NAMES = ("pp", "dp", "fsdp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """``pp`` is outermost: pipeline traffic is neighbor-to-neighbor once per
    microbatch, the most DCN-tolerant axis, so it spans slice boundaries
    first (scaling-book recipe: pipeline across, shard within)."""

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.pp * self.dp * self.fsdp * self.ep * self.tp * self.sp

    def axis_sizes(self) -> tuple[int, ...]:
        return (self.pp, self.dp, self.fsdp, self.ep, self.tp, self.sp)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a Mesh. ``make_mesh(dp=2, tp=4)`` or ``make_mesh(MeshConfig(...))``.

    One axis may be -1 (inferred from the device count, like a reshape).
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")
    devices = list(devices if devices is not None else jax.devices())
    sizes = list(config.axis_sizes())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by {known}"
            )
        sizes[sizes.index(-1)] = len(devices) // known
    if math.prod(sizes) != len(devices):
        raise ValueError(
            f"mesh {dict(zip(AXIS_NAMES, sizes))} needs {math.prod(sizes)} "
            f"devices, have {len(devices)}"
        )
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, AXIS_NAMES)


def make_hybrid_mesh(
    ici: MeshConfig,
    dcn: MeshConfig,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: ``dcn`` axes span slice boundaries, ``ici`` axes
    stay inside a slice.  Each named axis ends up with size
    ``dcn_axis * ici_axis``, with the DCN factor outermost within the axis —
    so e.g. ``ici=MeshConfig(fsdp=4), dcn=MeshConfig(dp=2)`` on 2 slices of
    4 chips gives a (dp=2, fsdp=4) mesh where gradient all-reduce crosses
    DCN once per step while param all-gathers ride ICI.

    Scaling-book recipe: only step-amortized traffic (dp, pp) should cross
    slices; per-layer collectives (tp, sp) must stay on ICI.  Nothing
    enforces that here, but the axis convention makes the safe layout the
    natural one.

    On real multi-slice TPU (devices carry ``slice_index``) the JAX
    ``mesh_utils.create_hybrid_device_mesh`` assignment is used; elsewhere
    (virtual CPU devices, tests) devices are treated as slice-major
    contiguous blocks.
    """
    devices = list(devices if devices is not None else jax.devices())
    ici_sizes, dcn_sizes = ici.axis_sizes(), dcn.axis_sizes()
    n_slices = math.prod(dcn_sizes)
    per_slice = math.prod(ici_sizes)
    if n_slices * per_slice != len(devices):
        raise ValueError(
            f"hybrid mesh ici={ici_sizes} x dcn={dcn_sizes} needs "
            f"{n_slices * per_slice} devices, have {len(devices)}"
        )
    if all(getattr(d, "slice_index", None) is not None for d in devices) and (
        len({d.slice_index for d in devices}) == n_slices
    ):
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devices
        )
        return Mesh(arr, AXIS_NAMES)
    # Fallback: slice-major contiguous blocks (process order groups hosts of
    # one slice together under the platform's pod-index worker layout).
    arr = np.asarray(devices).reshape(tuple(dcn_sizes) + tuple(ici_sizes))
    n = len(AXIS_NAMES)
    interleave = [k for i in range(n) for k in (i, i + n)]
    arr = arr.transpose(interleave).reshape(
        [d * i for d, i in zip(dcn_sizes, ici_sizes)]
    )
    return Mesh(arr, AXIS_NAMES)


def default_mesh_config(n_devices: int) -> MeshConfig:
    """Reasonable split for a given device count: favor fsdp, give tp the
    innermost factor once the slice is big enough to pay for it."""
    if n_devices == 1:
        return MeshConfig()
    tp = 1
    for cand in (8, 4, 2):
        if n_devices % cand == 0 and n_devices // cand >= 2:
            tp = cand
            break
    if n_devices % tp or n_devices // tp < 1:
        tp = 1
    rest = n_devices // tp
    # Split the remainder between dp and fsdp: fsdp gets everything by
    # default (params sharded as widely as possible).
    return MeshConfig(dp=1, fsdp=rest, tp=tp, sp=1)
