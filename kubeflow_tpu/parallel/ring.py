"""Ring attention: exact attention over sequence shards via ICI neighbor
exchange (context parallelism).

Each device holds a contiguous sequence chunk of q/k/v.  K/V chunks rotate
around the ``sp`` ring with ``lax.ppermute`` while every device folds each
visiting block into a running (max, denom, accumulator) — the flash-attention
merge applied across devices, so the full [S, S] score matrix never exists
anywhere and sequence length scales linearly with ring size.

This is the long-context path the reference platform has no analogue for
(SURVEY.md §5.7): there, long-context is "whatever the user runs"; here it is
a library call:

    with mesh:
        out = ring_attention(q, k, v, mesh=mesh, causal=True)

Causality across chunks uses global positions: block j vs. query chunk i is
fully-masked (skipped via where), diagonal (triangular mask), or dense.
Compute is overlapped with the ppermute by XLA's async collectives.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, *, scale, mode, q_offset, k_offset):
    """Attention over one (q-chunk, k-block) pair → (out*l, m, l) pieces.

    mode: 0 = dense, 1 = causal-diagonal, 2 = masked-out (returns -inf m).
    Shapes: q [b, sq, h, d]; k/v [b, sk, kh, d].  Returns f32.
    """
    from kubeflow_tpu.ops.attention import _repeat_kv

    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    sq, sk = q.shape[1], k.shape[1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + q_offset
    cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1) + k_offset
    causal_mask = rows[None, None] >= cols[None, None]
    # mode==1: apply triangular mask; mode==2: everything masked.
    logits = jnp.where(mode == 1, jnp.where(causal_mask, logits, _NEG_INF), logits)
    logits = jnp.where(mode == 2, _NEG_INF, logits)
    m = jnp.max(logits, axis=-1, keepdims=True)  # [b,h,sq,1]
    # Guard fully-masked rows.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(logits - m_safe)
    p = jnp.where(m <= _NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)  # [b,h,sq,1]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return pv, m_safe, l


def _flash_block_attn(q, k_blk, v_blk, *, scale, mode):
    """Flash-kernel version of one (q-chunk, k-block) fold: the [s, s]
    block logits never materialize (ops/pallas/flash_attention.py), and the
    lse output feeds the cross-device merge.  Returns the same
    (pv, m, l)-triple contract as _block_attn with the normalized
    convention (pv = normalized out, m = lse, l = 1); a fully-masked block
    is (0, -inf, 0).  Differentiable: flash_attention_with_lse carries the
    lse cotangent through its backward kernels."""
    from kubeflow_tpu.ops.pallas.flash_attention import flash_attention_with_lse

    b, s, h, d = q.shape

    def attended(causal_blk):
        def fn(q, k_blk, v_blk):
            out, lse = flash_attention_with_lse(
                q, k_blk, v_blk, causal=causal_blk, softmax_scale=scale
            )
            # lse: lane-replicated [b, h, s, 128] -> [b, h, s, 1].
            return (
                out.astype(jnp.float32),
                lse[..., 0:1],
                jnp.ones((b, h, s, 1), jnp.float32),
            )
        return fn

    def masked(q, k_blk, v_blk):
        return (
            jnp.zeros((b, s, h, d), jnp.float32),
            jnp.full((b, h, s, 1), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, s, 1), jnp.float32),
        )

    return jax.lax.switch(
        mode, [attended(False), attended(True), masked], q, k_blk, v_blk
    )


def _ring_attention_local(q, k, v, *, axis_name, causal, scale, use_flash):
    """Body run per-device under shard_map. q/k/v: local chunks [b,s,h,d]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q32 = q.astype(jnp.float32)

    def step(carry, r):
        k_blk, v_blk, acc, m, l = carry
        src_idx = (my_idx - r) % axis_size  # whose chunk we currently hold
        if causal:
            mode = jnp.where(
                src_idx == my_idx, 1, jnp.where(src_idx < my_idx, 0, 2)
            )
        else:
            mode = jnp.zeros((), jnp.int32)
        if use_flash:
            pv, bm, bl = _flash_block_attn(
                q, k_blk, v_blk, scale=scale, mode=mode
            )
        else:
            pv, bm, bl = _block_attn(
                q32,
                k_blk.astype(jnp.float32),
                v_blk.astype(jnp.float32),
                scale=scale,
                mode=mode,
                q_offset=my_idx * s_local,
                k_offset=src_idx * s_local,
            )
        # Online merge: bm/bl are [b,h,sq,1]; acc is [b,sq,h,d].
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = alpha * l + beta * bl
        # [b,h,sq,1] -> [b,sq,h,1] to scale BSHD accumulators.
        tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
        acc_new = acc * tr(alpha) + pv * tr(beta)
        # Rotate kv to the next device (ring).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc_new, m_new, l_new), None

    b, s, h, d = q.shape
    acc0 = jnp.zeros((b, s, h, d), jnp.float32)
    m0 = jnp.full((b, h, s, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s, 1), jnp.float32)
    (k_f, v_f, acc, m, l), _ = jax.lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(axis_size)
    )
    l_t = jnp.transpose(l, (0, 2, 1, 3))
    out = acc / jnp.where(l_t == 0.0, 1.0, l_t)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = "sp",
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    block_impl: str = "auto",
):
    """Exact attention with the sequence dimension sharded over ``axis_name``.

    Inputs are global-view BSHD arrays (sharded or shardable on seq); output
    has the same sharding.  Works under jit and composes with dp/fsdp/tp on
    the other mesh axes.

    ``block_impl``: "auto" | "einsum" | "flash" — how each visiting
    (q-chunk, k-block) pair is folded.  "flash" routes blocks through the
    Pallas kernel (no [s_local, s_local] logits materialization); "auto"
    selects it on TPU once the local chunk passes the kernel's
    ``should_use`` threshold (same gate as ops.dot_product_attention).
    """
    from kubeflow_tpu.parallel.sharding import data_axes

    if block_impl not in ("auto", "einsum", "flash"):
        raise ValueError(f"unknown block_impl {block_impl!r}")
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    ring_size = mesh.shape[axis_name]
    s_local = q.shape[1] // ring_size
    if block_impl == "einsum":
        use_flash = False
    else:
        from kubeflow_tpu.ops.pallas import flash_attention as fa

        local_shape = jax.ShapeDtypeStruct(
            (q.shape[0], s_local, q.shape[2], q.shape[3]), q.dtype
        )
        local_kv = jax.ShapeDtypeStruct(
            (k.shape[0], s_local, k.shape[2], k.shape[3]), k.dtype
        )
        ok = fa.supported(local_shape, local_kv, local_kv)
        if block_impl == "flash":
            if not ok:
                raise ValueError(
                    "flash block_impl unsupported for local chunk shape "
                    f"{local_shape.shape}"
                )
            use_flash = True
        else:
            # should_use gates on platform (TPU only — interpret mode on
            # CPU would be drastically slower) and local chunk length.
            use_flash = ok and fa.should_use(local_shape)
    spec = P(data_axes(mesh), axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=axis_name, causal=causal,
            scale=scale, use_flash=use_flash,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
