"""SPMD parallelism over TPU meshes.

The reference platform's "distributed backend" is nothing but pod scheduling
(SURVEY.md §5.8): NCCL/MPI never appear; multi-device is the user's problem.
In the TPU rebuild the compute-side story is explicit and first-class:

* ``mesh``     — build ``jax.sharding.Mesh``es over (dp, fsdp, ep, tp, sp) axes;
  ICI-friendly axis ordering.
* ``sharding`` — param-pytree partition rules (Megatron-style TP + FSDP) that
  keep models mesh-agnostic.
* ``train``    — wrap a pure train step in ``jax.jit`` with NamedShardings.
* ``ring``     — ring attention (sequence/context parallelism over ICI) via
  ``shard_map`` + ``ppermute``.
* ``dist``     — multi-host bring-up: ``jax.distributed.initialize`` from the
  TPU worker env the platform's webhook injects into notebook pods.
"""

from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
from kubeflow_tpu.parallel.mesh import default_mesh_config
from kubeflow_tpu.parallel.sharding import (
    batch_sharding,
    bert_rules,
    infer_state_shardings,
    llama_rules,
    resnet_rules,
    shard_params,
    t5_rules,
    vit_rules,
)
from kubeflow_tpu.parallel.train import make_sharded_train_step

__all__ = [
    "MeshConfig",
    "make_mesh",
    "default_mesh_config",
    "bert_rules",
    "resnet_rules",
    "t5_rules",
    "vit_rules",
    "batch_sharding",
    "infer_state_shardings",
    "llama_rules",
    "shard_params",
    "make_sharded_train_step",
    "ring_attention",
    "ulysses_attention",
    "pipeline_apply",
]


def __getattr__(name):  # lazy: ring/ulysses/pipeline pull in shard_map deps
    if name == "ring_attention":
        from kubeflow_tpu.parallel.ring import ring_attention

        return ring_attention
    if name == "ulysses_attention":
        from kubeflow_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention
    if name == "pipeline_apply":
        from kubeflow_tpu.parallel.pipeline import pipeline_apply

        return pipeline_apply
    raise AttributeError(name)
