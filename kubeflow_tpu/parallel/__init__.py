"""SPMD parallelism over TPU meshes.

The reference platform's "distributed backend" is nothing but pod scheduling
(SURVEY.md §5.8): NCCL/MPI never appear; multi-device is the user's problem.
In the TPU rebuild the compute-side story is explicit and first-class:

* ``mesh``     — build ``jax.sharding.Mesh``es over (dp, fsdp, ep, tp, sp) axes;
  ICI-friendly axis ordering.
* ``sharding`` — param-pytree partition rules (Megatron-style TP + FSDP) that
  keep models mesh-agnostic.
* ``train``    — wrap a pure train step in ``jax.jit`` with NamedShardings.
* ``ring``     — ring attention (sequence/context parallelism over ICI) via
  ``shard_map`` + ``ppermute``.
* ``dist``     — multi-host bring-up: ``jax.distributed.initialize`` from the
  TPU worker env the platform's webhook injects into notebook pods.
* ``envspec``  — the worker env contract shared with the platform controllers;
  deliberately jax-free, which is why EVERYTHING here is lazy: the platform
  half does ``from kubeflow_tpu.parallel import envspec`` on reconcile paths
  that must not pay (or even have) the jax import.
"""

__all__ = [
    "MeshConfig",
    "make_mesh",
    "default_mesh_config",
    "bert_rules",
    "resnet_rules",
    "t5_rules",
    "vit_rules",
    "batch_sharding",
    "infer_state_shardings",
    "llama_rules",
    "shard_params",
    "make_sharded_train_step",
    "ring_attention",
    "ulysses_attention",
    "pipeline_apply",
]

_LAZY = {
    "MeshConfig": "mesh",
    "make_mesh": "mesh",
    "default_mesh_config": "mesh",
    "batch_sharding": "sharding",
    "bert_rules": "sharding",
    "infer_state_shardings": "sharding",
    "llama_rules": "sharding",
    "resnet_rules": "sharding",
    "shard_params": "sharding",
    "t5_rules": "sharding",
    "vit_rules": "sharding",
    "make_sharded_train_step": "train",
    "ring_attention": "ring",
    "ulysses_attention": "ulysses",
    "pipeline_apply": "pipeline",
}


def __getattr__(name):  # PEP 562: every symbol lazy — see envspec note above
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(name)
    import importlib

    return getattr(
        importlib.import_module(f"kubeflow_tpu.parallel.{module}"), name)
