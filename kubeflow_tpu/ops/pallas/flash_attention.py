"""Flash attention forward kernel for TPU (Pallas/Mosaic).

Online-softmax blocked attention: the [Sq, Sk] logits matrix never
materializes in HBM; each (q-block, k-block) tile is computed in VMEM and
folded into a running (max, sum, accumulator) — the standard flash recipe
laid out for the MXU:

* QK^T and PV contractions hit the 128x128 systolic array with
  ``preferred_element_type=f32`` accumulation.
* Running max/denominator live in (block_q, 128) VMEM scratch (lane-replicated
  scalars — the VPU's native (8,128) shape; a (block_q, 1) buffer would pad to
  128 lanes anyway).
* The kv grid axis is ``arbitrary`` (sequential) so scratch carries across
  iterations; batch/head/q axes are ``parallel``.
* Causal masking skips fully-masked kv blocks via ``pl.when`` — ~2x fewer
  tiles at long sequence.

Backward: recompute-based VJP (forward kernel + XLA attention vjp on the
saved residuals).  A blocked Pallas backward is a follow-up; recompute is
correct and keeps memory O(S) rather than O(S^2) only in the fwd pass.

On non-TPU backends the same kernel runs in interpret mode (used by the CPU
test suite), but ``should_use`` only selects it on real TPU.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas extras are unavailable on pure-CPU builds.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _platform() -> str:
    return jax.devices()[0].platform


def supported(q, k, v, *, bias=None, segment_ids=None) -> bool:
    """Shape gate for the kernel; the public op falls back to XLA otherwise."""
    if pltpu is None:
        return False
    if bias is not None or segment_ids is not None:
        return False
    b, sq, hq, d = q.shape
    _, sk, hk, dk = k.shape
    if d != dk or v.shape != k.shape:
        return False
    if hq % hk != 0:
        return False
    if sq != sk:
        # The kernel's causal mask is diagonal-aligned at q_start == k_start;
        # cross-length (decode-style) shapes take the XLA path, which uses
        # end-aligned masking (tril offset sk-sq).
        return False
    if d % 64 != 0 or d > 256:
        return False
    bq = min(DEFAULT_BLOCK_Q, sq)
    bk = min(DEFAULT_BLOCK_K, sk)
    return sq % bq == 0 and sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


def should_use(q) -> bool:
    """Heuristic: flash wins once the S^2 logits stop fitting cache/VMEM."""
    if _platform() not in ("tpu", "axon"):
        return False
    return q.shape[1] >= 1024


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, causal, scale, block_q, block_k, num_k
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Under causal masking, a kv block strictly above the diagonal band is
    # dead; skip its flops entirely.
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]  # (block_q, 128), lane-replicated
        row_max = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, row_max)
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 128)
        p = jnp.exp(s - m_new[:, 0:1])
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, 0:1] + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[...][:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal, softmax_scale, block_q, block_k, interpret):
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    n_rep = hq // hk
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    num_k = sk // bk

    # BHSD layout inside the kernel: the (seq, head_dim) tile is the MXU
    # operand, batch/head are pure grid axes.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, hq, sq // bq, num_k)
    kernel = functools.partial(
        _fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=bq,
        block_k=bk,
        num_k=num_k,
    )
    params = {}
    if pltpu is not None and not interpret:
        semantics = ("parallel", "parallel", "parallel", "arbitrary")
        if hasattr(pltpu, "CompilerParams"):
            params["compiler_params"] = pltpu.CompilerParams(
                dimension_semantics=semantics
            )
        else:  # pragma: no cover - older jax
            params["compiler_params"] = pltpu.TPUCompilerParams(
                dimension_semantics=semantics
            )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec(
                (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),  # acc
            pltpu.VMEM((bq, 128), jnp.float32),  # m (lane-replicated row max)
            pltpu.VMEM((bq, 128), jnp.float32),  # l (lane-replicated row sum)
        ],
        interpret=interpret,
        **params,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, softmax_scale, block_q, block_k):
    interpret = _platform() not in ("tpu", "axon")
    return _flash_fwd(
        q,
        k,
        v,
        causal=causal,
        softmax_scale=softmax_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
):
    """Flash attention, BSHD layout, GQA via fewer kv heads."""
    return _flash_attention(q, k, v, causal, softmax_scale, block_q, block_k)


def _vjp_fwd(q, k, v, causal, softmax_scale, block_q, block_k):
    out = _flash_attention(q, k, v, causal, softmax_scale, block_q, block_k)
    return out, (q, k, v)


def _vjp_bwd(causal, softmax_scale, block_q, block_k, res, g):
    # Recompute-based backward through the XLA reference; numerically the
    # same attention, and XLA's fused vjp is solid on TPU.  A blocked Pallas
    # dq/dk/dv kernel can replace this without touching callers.
    from kubeflow_tpu.ops.attention import xla_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_attention(
            q_, k_, v_, causal=causal, softmax_scale=softmax_scale
        ),
        q,
        k,
        v,
    )
    return vjp(g)


_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
