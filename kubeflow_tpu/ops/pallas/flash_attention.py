"""Flash attention forward kernel for TPU (Pallas/Mosaic).

Online-softmax blocked attention: the [Sq, Sk] logits matrix never
materializes in HBM; each (q-block, k-block) tile is computed in VMEM and
folded into a running (max, sum, accumulator) — the standard flash recipe
laid out for the MXU:

* QK^T and PV contractions hit the 128x128 systolic array with
  ``preferred_element_type=f32`` accumulation.
* Running max/denominator live in (block_q, 128) VMEM scratch (lane-replicated
  scalars — the VPU's native (8,128) shape; a (block_q, 1) buffer would pad to
  128 lanes anyway).
* The kv grid axis is ``arbitrary`` (sequential) so scratch carries across
  iterations; batch/head/q axes are ``parallel``.
* Causal masking skips fully-masked kv blocks via ``pl.when`` — ~2x fewer
  tiles at long sequence.

Backward: blocked Pallas kernels (FlashAttention-2 style).  The forward
saves only the per-row logsumexp (lane-replicated [b, h, s, 128], the
official TPU kernel's layout); the backward recomputes P per tile in two
passes — dq with kv sequential, dk/dv with q sequential (GQA heads
group-summed after) — so memory stays O(S) end to end.  Measured on v5e:
1.5x XLA's vjp at 4k sequence, ~12x at 8k (where XLA's O(S^2) logits
materialization starts thrashing HBM).

On non-TPU backends the same kernel runs in interpret mode (used by the CPU
test suite), but ``should_use`` only selects it on real TPU.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas extras are unavailable on pure-CPU builds.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30


def _platform() -> str:
    return jax.devices()[0].platform


def supported(q, k, v, *, bias=None, segment_ids=None) -> bool:
    """Shape gate for the kernel; the public op falls back to XLA otherwise."""
    if pltpu is None:
        return False
    if bias is not None or segment_ids is not None:
        return False
    b, sq, hq, d = q.shape
    _, sk, hk, dk = k.shape
    if d != dk or v.shape != k.shape:
        return False
    if hq % hk != 0:
        return False
    if sq != sk:
        # The kernel's causal mask is diagonal-aligned at q_start == k_start;
        # cross-length (decode-style) shapes take the XLA path, which uses
        # end-aligned masking (tril offset sk-sq).
        return False
    if d % 64 != 0 or d > 256:
        return False
    bq = min(DEFAULT_BLOCK_Q, sq)
    bk = min(DEFAULT_BLOCK_K, sk)
    return sq % bq == 0 and sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


def should_use(q) -> bool:
    """Heuristic: flash wins once the S^2 logits stop fitting cache/VMEM."""
    if _platform() not in ("tpu", "axon"):
        return False
    return q.shape[1] >= 1024


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
    causal, scale, block_q, block_k, num_k
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Under causal masking, a kv block strictly above the diagonal band is
    # dead; skip its flops entirely.
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = (q_start + rows) >= (k_start + cols)
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]  # (block_q, 128), lane-replicated
        row_max = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, row_max)
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 128)
        p = jnp.exp(s - m_new[:, 0:1])
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, 0:1] + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[...][:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # Per-row logsumexp residual for the backward pass,
            # lane-replicated (the official TPU kernel's layout).
            lse_ref[0, 0] = m_ref[...] + jnp.log(l_ref[...])


def _compiler_params(interpret, semantics):
    if pltpu is None or interpret:
        return {}
    if hasattr(pltpu, "CompilerParams"):
        return {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=semantics)}
    return {"compiler_params": pltpu.TPUCompilerParams(  # pragma: no cover
        dimension_semantics=semantics)}


def _scratch(shape, dtype=jnp.float32):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


def _flash_fwd(q, k, v, *, causal, softmax_scale, block_q, block_k, interpret,
               return_residuals=False):
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    n_rep = hq // hk
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    num_k = sk // bk

    # BHSD layout inside the kernel: the (seq, head_dim) tile is the MXU
    # operand, batch/head are pure grid axes.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, hq, sq // bq, num_k)
    base = functools.partial(
        _fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=bq,
        block_k=bk,
        num_k=num_k,
    )
    if return_residuals:
        kernel = base
        out_shape = [
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 128), jnp.float32),  # lse
        ]
        out_specs = [
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ]
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
            base(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)

        out_shape = jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype)
        out_specs = pl.BlockSpec(
            (1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec(
                (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
            ),
            pl.BlockSpec(
                (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
            ),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _scratch((bq, d)),    # acc
            _scratch((bq, 128)),  # m (lane-replicated row max)
            _scratch((bq, 128)),  # l (lane-replicated row sum)
        ],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(qt, kt, vt)
    if return_residuals:
        o, lse = out
        return o.transpose(0, 2, 1, 3), lse
    return out.transpose(0, 2, 1, 3)


def _bwd_tile(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref, *,
              causal, scale, q_start, k_start, block_q, block_k):
    """Shared backward tile math: (p, ds, do) for one (q, k) block pair.
    delta = rowsum(dO ∘ O) is recomputed here from the residuals instead of
    being materialized lane-replicated in HBM (it is one scalar per row; a
    (bq, d) elementwise pass in VMEM is cheaper than 128x HBM traffic).
    ``glse_ref`` (optional) carries the cotangent of the lse output when
    the caller consumed it (flash_attention_with_lse): d lse_i/d s_ij = p_ij,
    so it enters as an extra per-row term inside the ds product.  The mask
    convention must stay identical to _fwd_kernel's."""
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    o = o_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0:1]  # (bq, 1), lane-replicated source
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # (bq, 1)
    if glse_ref is not None:
        # The forward replicated lse across 128 lanes; the per-row scalar
        # cotangent is the SUM over lane cotangents (consumers typically
        # slice one lane, leaving zeros elsewhere — the sum covers both).
        delta = delta - jnp.sum(
            glse_ref[0, 0].astype(jnp.float32), axis=-1, keepdims=True
        )
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where((q_start + rows) >= (k_start + cols), s, _NEG_INF)
    p = jnp.exp(s - lse)  # (bq, bk)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * scale  # (bq, bk)
    return q, k, p, ds, do


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref, dq_ref,
               acc_ref, *, causal, scale, block_q, block_k, num_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        _, k, _, ds, _ = _bwd_tile(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
            causal=causal, scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_k=block_k,
        )
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dq_kernel_noglse(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                      acc_ref, **kw):
    _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, None, dq_ref,
               acc_ref, **kw)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                causal, scale, block_q, block_k, num_q):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q, _, p, ds, do = _bwd_tile(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
            causal=causal, scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_k=block_k,
        )
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _dkv_kernel_noglse(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, **kw):
    _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, None,
                dk_ref, dv_ref, dk_acc, dv_acc, **kw)


def _flash_bwd(q, k, v, out, lse, g, *, causal, softmax_scale, block_q,
               block_k, interpret, g_lse=None):
    """Blocked FlashAttention-2 backward: a dq pass (kv sequential) and a
    dk/dv pass (q sequential).  GQA: dk/dv are produced per q-head and
    group-summed in XLA afterwards.  ``g_lse`` is the cotangent of the lse
    output for the with-lse variant (None for plain flash_attention)."""
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    n_rep = hq // hk
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    num_q, num_k = sq // bq, sk // bk

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)
    with_glse = g_lse is not None
    extra = (g_lse,) if with_glse else ()

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, bk, d),
        lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0),
    )
    lse_spec = pl.BlockSpec(
        (1, 1, bq, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )
    dq_kernel = _dq_kernel if with_glse else _dq_kernel_noglse
    dq = pl.pallas_call(
        functools.partial(
            dq_kernel, causal=causal, scale=scale,
            block_q=bq, block_k=bk, num_k=num_k,
        ),
        grid=(b, hq, num_q, num_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec]
        + ([lse_spec] if with_glse else []),
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[_scratch((bq, d))],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(qt, kt, vt, ot, dot, lse, *extra)

    # dk/dv: grid ordered (k, q) so the q axis is the sequential one.
    q_spec2 = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kv_spec2 = pl.BlockSpec(
        (1, 1, bk, d),
        lambda bi, hi, ki, qi, n_rep=n_rep: (bi, hi // n_rep, ki, 0),
    )
    lse_spec2 = pl.BlockSpec(
        (1, 1, bq, 128), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    )
    dkv_out_spec = pl.BlockSpec(
        (1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    )
    dkv_kernel = _dkv_kernel if with_glse else _dkv_kernel_noglse
    dk, dv = pl.pallas_call(
        functools.partial(
            dkv_kernel, causal=causal, scale=scale,
            block_q=bq, block_k=bk, num_q=num_q,
        ),
        grid=(b, hq, num_k, num_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2, lse_spec2]
        + ([lse_spec2] if with_glse else []),
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, sk, d), v.dtype),
        ],
        scratch_shapes=[_scratch((bk, d)), _scratch((bk, d))],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(qt, kt, vt, ot, dot, lse, *extra)

    if n_rep > 1:
        dk = dk.reshape(b, hk, n_rep, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, n_rep, sk, d).sum(axis=2)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, softmax_scale, block_q, block_k):
    interpret = _platform() not in ("tpu", "axon")
    return _flash_fwd(
        q,
        k,
        v,
        causal=causal,
        softmax_scale=softmax_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )


def default_blocks(sq: int, sk: int) -> tuple:
    """Measured block-size heuristic (v5e block study, BASELINE.md): bigger
    tiles amortize per-grid-cell overhead as sequence grows — 2.3x faster
    at seq 8192 with 1024x1024 vs the 256x256 floor — until VMEM bounds
    them (2048 tiles fail to compile at d=128).  Ragged lengths fall back
    to the floor, which divides everything supported() admits."""
    bq = min(1024, max(DEFAULT_BLOCK_Q, (sq // 8) // 8 * 8))
    bk = min(1024, max(DEFAULT_BLOCK_K, (sk // 8) // 128 * 128))
    if sq % bq:
        bq = min(DEFAULT_BLOCK_Q, sq)
    if sk % bk:
        bk = min(DEFAULT_BLOCK_K, sk)
    return bq, bk


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Flash attention, BSHD layout, GQA via fewer kv heads.  Block sizes
    default to the measured sequence-length heuristic (default_blocks)."""
    if block_q is None or block_k is None:
        auto_q, auto_k = default_blocks(q.shape[1], k.shape[1])
        block_q = auto_q if block_q is None else block_q
        block_k = auto_k if block_k is None else block_k
    return _flash_attention(q, k, v, causal, softmax_scale, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_with_lse(q, k, v, causal, softmax_scale, block_q,
                              block_k):
    interpret = _platform() not in ("tpu", "axon")
    return _flash_fwd(
        q, k, v, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        return_residuals=True,
    )


def flash_attention_with_lse(
    q, k, v, *, causal: bool = False,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Flash attention that also returns the per-row logsumexp
    (lane-replicated [b, h, sq, 128] f32) — the residual block-merging
    consumers need (ring attention's cross-device flash merge).  Fully
    differentiable including the lse output."""
    if block_q is None or block_k is None:
        auto_q, auto_k = default_blocks(q.shape[1], k.shape[1])
        block_q = auto_q if block_q is None else block_q
        block_k = auto_k if block_k is None else block_k
    return _flash_attention_with_lse(
        q, k, v, causal, softmax_scale, block_q, block_k
    )


def _with_lse_fwd(q, k, v, causal, softmax_scale, block_q, block_k):
    out, lse = _flash_attention_with_lse(
        q, k, v, causal, softmax_scale, block_q, block_k
    )
    return (out, lse), (q, k, v, out, lse)


def _with_lse_bwd(causal, softmax_scale, block_q, block_k, res, cotangents):
    q, k, v, out, lse = res
    g_out, g_lse = cotangents
    interpret = _platform() not in ("tpu", "axon")
    return _flash_bwd(
        q, k, v, out, lse, g_out, causal=causal,
        softmax_scale=softmax_scale, block_q=block_q, block_k=block_k,
        interpret=interpret, g_lse=g_lse.astype(jnp.float32),
    )


_flash_attention_with_lse.defvjp(_with_lse_fwd, _with_lse_bwd)


def _vjp_fwd(q, k, v, causal, softmax_scale, block_q, block_k):
    # Under differentiation the forward additionally emits the per-row
    # logsumexp — the only residual the blocked backward needs beyond the
    # inputs and output (recomputing P per tile, FlashAttention-2 style).
    interpret = _platform() not in ("tpu", "axon")
    out, lse = _flash_fwd(
        q, k, v, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        return_residuals=True,
    )
    return out, (q, k, v, out, lse)


def _vjp_bwd(causal, softmax_scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    interpret = _platform() not in ("tpu", "axon")
    return _flash_bwd(
        q, k, v, out, lse, g, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
