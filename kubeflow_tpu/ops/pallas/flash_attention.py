"""Flash attention forward kernel for TPU (Pallas/Mosaic).

Online-softmax blocked attention: the [Sq, Sk] logits matrix never
materializes in HBM; each (q-block, k-block) tile is computed in VMEM and
folded into a running (max, sum, accumulator) — the standard flash recipe
laid out for the MXU:

* QK^T and PV contractions hit the 128x128 systolic array with
  ``preferred_element_type=f32`` accumulation.
* Running max/denominator live in (block_q, 128) VMEM scratch (lane-replicated
  scalars — the VPU's native (8,128) shape; a (block_q, 1) buffer would pad to
  128 lanes anyway).
* The kv grid axis is ``arbitrary`` (sequential) so scratch carries across
  iterations; batch/head/q axes are ``parallel``.
* Causal masking skips fully-masked kv blocks via ``pl.when`` — ~2x fewer
  tiles at long sequence.  Cross-length causal shapes (sq < sk, the ragged
  prefill / decode-style case) use the END-ALIGNED convention: query row i
  sees keys up to i + (sk - sq), matching ``xla_attention``'s tril offset.
* Packed sequences: ``segment_ids`` ([b, s] int, 0 = padding) mask
  cross-document attention inside each tile.  The q ids ride lane-replicated
  ([b, s, 128] — the lse layout) and the kv ids sublane-replicated
  ([b, 8, s]), so each tile's compare is one VPU broadcast; rows whose
  segment has no match in a tile zero their probs explicitly (the running
  max is still the init sentinel there, so exp(s - m) would read 1).

Backward: blocked Pallas kernels (FlashAttention-2 style).  The forward
saves only the per-row logsumexp (lane-replicated [b, h, s, 128], the
official TPU kernel's layout); the backward recomputes P per tile in two
passes — dq with kv sequential, dk/dv with q sequential (GQA heads
group-summed after) — so memory stays O(S) end to end.  Measured on v5e:
1.5x XLA's vjp at 4k sequence, ~12x at 8k (where XLA's O(S^2) logits
materialization starts thrashing HBM).

On non-TPU backends the same kernel runs in interpret mode (used by the CPU
test suite), but ``should_use`` only selects it on real TPU — where it now
weighs the masked XLA path's O(S²) footprint against free HBM (the
BENCH_r05 crash mode) on top of the measured seq-length crossover.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas extras are unavailable on pure-CPU builds.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = -1e30
# Segment-id operand layout: q ids lane-replicated (like the lse residual),
# kv ids sublane-replicated — the minimal legal int32 tiles.
_SEG_LANES = 128
_SEG_SUBLANES = 8


def _platform() -> str:
    return jax.devices()[0].platform


def supported(q, k, v, *, bias=None, segment_ids=None, causal=False) -> bool:
    """Shape gate for the kernel; the public op falls back to XLA otherwise.

    Bias stays XLA-only (no bias tiles in the kernel).  Cross-length
    shapes are admitted — causal uses the end-aligned offset, so causal
    requires sq <= sk (sq > sk would leave the leading rows fully masked,
    which the XLA path defines as a uniform softmax and the kernel does
    not).  ``segment_ids`` (packed training) requires sq == sk: one id
    vector describes both sides, exactly the public op's contract.
    """
    if pltpu is None:
        return False
    if bias is not None:
        return False
    b, sq, hq, d = q.shape
    _, sk, hk, dk = k.shape
    if d != dk or v.shape != k.shape:
        return False
    if hq % hk != 0:
        return False
    if causal and sq > sk:
        return False
    if segment_ids is not None:
        if sq != sk:
            return False
        if tuple(segment_ids.shape) != (b, sq):
            return False
        if not jnp.issubdtype(segment_ids.dtype, jnp.integer):
            return False
    if d % 64 != 0 or d > 256:
        return False
    bq = min(DEFAULT_BLOCK_Q, sq)
    bk = min(DEFAULT_BLOCK_K, sk)
    return sq % bq == 0 and sk % bk == 0 and bq % 8 == 0 and bk % 128 == 0


def should_use(q, k=None, *, causal=False, segments=False) -> bool:
    """Routing heuristic for ``impl="auto"`` (only on real TPU; CPU always
    prefers XLA's fused path).  Two triggers, either is sufficient:

    * the masked XLA path's O(S²) footprint (attention_footprint_bytes)
      would cross ``ATTENTION_HBM_BUDGET_FRACTION`` of free HBM — the
      BENCH_r05 RESOURCE_EXHAUSTED mode, now a routing decision instead of
      a crash;
    * the measured seq-length crossover (flash wins once the S² logits
      stop fitting cache/VMEM — v5e kernel table, BASELINE.md).

    When the backend reports no memory stats only the crossover applies.
    """
    if _platform() not in ("tpu", "axon"):
        return False
    if q.shape[1] >= 1024:
        return True
    from kubeflow_tpu.ops.attention import attention_footprint_bytes
    from kubeflow_tpu.telemetry import compute as ctel

    k_len = q.shape[1] if k is None else k.shape[1]
    est = attention_footprint_bytes(
        batch=q.shape[0], heads=q.shape[2], q_len=q.shape[1], k_len=k_len,
        causal=causal, segments=segments,
    )
    free = ctel.free_hbm_bytes()
    if free is not None and est > ctel.ATTENTION_HBM_BUDGET_FRACTION * free:
        return True
    return False


def _tile_mask(qseg_ref, kseg_ref, *, causal, q_start, k_start, offset,
               block_q, block_k):
    """The (block_q, block_k) boolean visibility mask for one tile, or None
    when the tile is mask-free.  Shared by the forward and both backward
    passes — the mask convention MUST stay identical across them."""
    mask = None
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (q_start + rows + offset) >= (k_start + cols)
    if qseg_ref is not None:
        q_sids = qseg_ref[0][:, 0:1]   # (block_q, 1), lane-replicated source
        kv_sids = kseg_ref[0][0:1, :]  # (1, block_k), sublane-replicated
        seg = q_sids == kv_sids        # (block_q, block_k)
        mask = seg if mask is None else (mask & seg)
    return mask


def _fwd_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
    acc_ref, m_ref, l_ref, *,
    causal, scale, block_q, block_k, num_k, offset
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    # Under causal masking, a kv block strictly above the (offset) diagonal
    # band is dead; skip its flops entirely.
    run = True
    if causal:
        run = k_start <= q_start + offset + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        mask = _tile_mask(
            qseg_ref, kseg_ref, causal=causal, q_start=q_start,
            k_start=k_start, offset=offset, block_q=block_q, block_k=block_k,
        )
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]  # (block_q, 128), lane-replicated
        row_max = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, row_max)
        alpha = jnp.exp(m_prev - m_new)  # (block_q, 128)
        p = jnp.exp(s - m_new[:, 0:1])
        if qseg_ref is not None:
            # A row whose segment has no key in this tile is fully masked:
            # its running max is still the _NEG_INF sentinel, so
            # exp(s - m) above reads exp(0) = 1 on every masked slot —
            # zero those probs so dead tiles contribute nothing (the first
            # valid tile's alpha rescale then starts from a clean 0).
            p = jnp.where(mask, p, 0.0)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, 0:1] + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[...][:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # Per-row logsumexp residual for the backward pass,
            # lane-replicated (the official TPU kernel's layout).
            lse_ref[0, 0] = m_ref[...] + jnp.log(l_ref[...])


def _compiler_params(interpret, semantics):
    if pltpu is None or interpret:
        return {}
    if hasattr(pltpu, "CompilerParams"):
        return {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=semantics)}
    return {"compiler_params": pltpu.TPUCompilerParams(  # pragma: no cover
        dimension_semantics=semantics)}


def _scratch(shape, dtype=jnp.float32):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


def _seg_operands(segment_ids, b, sq, sk):
    """Expand [b, s] ids to the kernel's lane-/sublane-replicated layouts."""
    ids = segment_ids.astype(jnp.int32)
    qseg = jnp.broadcast_to(ids[:, :, None], (b, sq, _SEG_LANES))
    kseg = jnp.broadcast_to(ids[:, None, :], (b, _SEG_SUBLANES, sk))
    return qseg, kseg


def _flash_fwd(q, k, v, *, causal, softmax_scale, block_q, block_k, interpret,
               return_residuals=False, segment_ids=None):
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    n_rep = hq // hk
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    num_k = sk // bk
    # End-aligned causal: query row i sees keys up to i + (sk - sq) —
    # identical to xla_attention's tril(k=sk-sq) convention.
    offset = sk - sq if causal else 0
    has_seg = segment_ids is not None

    # BHSD layout inside the kernel: the (seq, head_dim) tile is the MXU
    # operand, batch/head are pure grid axes.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, hq, sq // bq, num_k)
    inputs = [qt, kt, vt]
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec(
            (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
        ),
        pl.BlockSpec(
            (1, 1, bk, d), lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0)
        ),
    ]
    if has_seg:
        qseg, kseg = _seg_operands(segment_ids, b, sq, sk)
        inputs += [qseg, kseg]
        in_specs += [
            pl.BlockSpec((1, bq, _SEG_LANES),
                         lambda bi, hi, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((1, _SEG_SUBLANES, bk),
                         lambda bi, hi, qi, ki: (bi, 0, ki)),
        ]

    base = functools.partial(
        _fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=bq,
        block_k=bk,
        num_k=num_k,
        offset=offset,
    )

    def kernel(*refs):
        i = 3
        qs = ks = None
        if has_seg:
            qs, ks = refs[i:i + 2]
            i += 2
        o_ref = refs[i]
        lse = refs[i + 1] if return_residuals else None
        acc_ref, m_ref, l_ref = refs[-3:]
        base(refs[0], refs[1], refs[2], qs, ks, o_ref, lse,
             acc_ref, m_ref, l_ref)

    if return_residuals:
        out_shape = [
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 128), jnp.float32),  # lse
        ]
        out_specs = [
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ]
    else:
        out_shape = jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype)
        out_specs = pl.BlockSpec(
            (1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _scratch((bq, d)),    # acc
            _scratch((bq, 128)),  # m (lane-replicated row max)
            _scratch((bq, 128)),  # l (lane-replicated row sum)
        ],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(*inputs)
    if return_residuals:
        o, lse = out
        return o.transpose(0, 2, 1, 3), lse
    return out.transpose(0, 2, 1, 3)


def _bwd_tile(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
              qseg_ref, kseg_ref, *,
              causal, scale, q_start, k_start, block_q, block_k, offset):
    """Shared backward tile math: (p, ds, do) for one (q, k) block pair.
    delta = rowsum(dO ∘ O) is recomputed here from the residuals instead of
    being materialized lane-replicated in HBM (it is one scalar per row; a
    (bq, d) elementwise pass in VMEM is cheaper than 128x HBM traffic).
    ``glse_ref`` (optional) carries the cotangent of the lse output when
    the caller consumed it (flash_attention_with_lse): d lse_i/d s_ij = p_ij,
    so it enters as an extra per-row term inside the ds product.  The mask
    convention must stay identical to _fwd_kernel's (_tile_mask)."""
    q = q_ref[0, 0].astype(jnp.float32)
    k = k_ref[0, 0].astype(jnp.float32)
    v = v_ref[0, 0].astype(jnp.float32)
    o = o_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0:1]  # (bq, 1), lane-replicated source
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # (bq, 1)
    if glse_ref is not None:
        # The forward replicated lse across 128 lanes; the per-row scalar
        # cotangent is the SUM over lane cotangents (consumers typically
        # slice one lane, leaving zeros elsewhere — the sum covers both).
        delta = delta - jnp.sum(
            glse_ref[0, 0].astype(jnp.float32), axis=-1, keepdims=True
        )
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    mask = _tile_mask(
        qseg_ref, kseg_ref, causal=causal, q_start=q_start, k_start=k_start,
        offset=offset, block_q=block_q, block_k=block_k,
    )
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jnp.exp(s - lse)  # (bq, bk)
    if qseg_ref is not None:
        # Mirror the forward's dead-tile guard: a fully-masked row carries
        # the sentinel lse, where exp(s - lse) reads 1 — zero it so dq/dk/dv
        # see no phantom probability mass.
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * scale  # (bq, bk)
    return q, k, p, ds, do


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
               qseg_ref, kseg_ref, dq_ref, acc_ref, *,
               causal, scale, block_q, block_k, num_k, offset):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + offset + block_q - 1

    @pl.when(run)
    def _compute():
        _, k, _, ds, _ = _bwd_tile(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
            qseg_ref, kseg_ref,
            causal=causal, scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_k=block_k, offset=offset,
        )
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
                qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                causal, scale, block_q, block_k, num_q, offset):
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = k_start <= q_start + offset + block_q - 1

    @pl.when(run)
    def _compute():
        q, _, p, ds, do = _bwd_tile(
            q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
            qseg_ref, kseg_ref,
            causal=causal, scale=scale, q_start=q_start, k_start=k_start,
            block_q=block_q, block_k=block_k, offset=offset,
        )
        dv_acc[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, *, causal, softmax_scale, block_q,
               block_k, interpret, g_lse=None, segment_ids=None):
    """Blocked FlashAttention-2 backward: a dq pass (kv sequential) and a
    dk/dv pass (q sequential).  GQA: dk/dv are produced per q-head and
    group-summed in XLA afterwards.  ``g_lse`` is the cotangent of the lse
    output for the with-lse variant (None for plain flash_attention)."""
    b, sq, hq, d = q.shape
    _, sk, hk, _ = k.shape
    n_rep = hq // hk
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    num_q, num_k = sq // bq, sk // bk
    offset = sk - sq if causal else 0
    has_seg = segment_ids is not None

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    dot = g.transpose(0, 2, 1, 3)
    with_glse = g_lse is not None
    extra = (g_lse,) if with_glse else ()
    seg_inputs = ()
    if has_seg:
        seg_inputs = _seg_operands(segment_ids, b, sq, sk)

    def unpack(refs):
        """(glse_ref, qseg_ref, kseg_ref) from the optional input tail."""
        i = 6
        glse = None
        if with_glse:
            glse = refs[i]
            i += 1
        qs = ks = None
        if has_seg:
            qs, ks = refs[i:i + 2]
        return glse, qs, ks

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, bk, d),
        lambda bi, hi, qi, ki, n_rep=n_rep: (bi, hi // n_rep, ki, 0),
    )
    lse_spec = pl.BlockSpec(
        (1, 1, bq, 128), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    )
    seg_specs = [
        pl.BlockSpec((1, bq, _SEG_LANES), lambda bi, hi, qi, ki: (bi, qi, 0)),
        pl.BlockSpec((1, _SEG_SUBLANES, bk),
                     lambda bi, hi, qi, ki: (bi, 0, ki)),
    ] if has_seg else []

    def dq_kernel(*refs):
        glse, qs, ks = unpack(refs)
        _dq_kernel(refs[0], refs[1], refs[2], refs[3], refs[4], refs[5],
                   glse, qs, ks, refs[-2], refs[-1],
                   causal=causal, scale=scale, block_q=bq, block_k=bk,
                   num_k=num_k, offset=offset)

    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, hq, num_q, num_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lse_spec]
        + ([lse_spec] if with_glse else []) + seg_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[_scratch((bq, d))],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(qt, kt, vt, ot, dot, lse, *extra, *seg_inputs)

    # dk/dv: grid ordered (k, q) so the q axis is the sequential one.
    q_spec2 = pl.BlockSpec((1, 1, bq, d), lambda bi, hi, ki, qi: (bi, hi, qi, 0))
    kv_spec2 = pl.BlockSpec(
        (1, 1, bk, d),
        lambda bi, hi, ki, qi, n_rep=n_rep: (bi, hi // n_rep, ki, 0),
    )
    lse_spec2 = pl.BlockSpec(
        (1, 1, bq, 128), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    )
    dkv_out_spec = pl.BlockSpec(
        (1, 1, bk, d), lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    )
    seg_specs2 = [
        pl.BlockSpec((1, bq, _SEG_LANES), lambda bi, hi, ki, qi: (bi, qi, 0)),
        pl.BlockSpec((1, _SEG_SUBLANES, bk),
                     lambda bi, hi, ki, qi: (bi, 0, ki)),
    ] if has_seg else []

    def dkv_kernel(*refs):
        glse, qs, ks = unpack(refs)
        _dkv_kernel(refs[0], refs[1], refs[2], refs[3], refs[4], refs[5],
                    glse, qs, ks, refs[-4], refs[-3], refs[-2], refs[-1],
                    causal=causal, scale=scale, block_q=bq, block_k=bk,
                    num_q=num_q, offset=offset)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, hq, num_k, num_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, q_spec2, lse_spec2]
        + ([lse_spec2] if with_glse else []) + seg_specs2,
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hq, sk, d), v.dtype),
        ],
        scratch_shapes=[_scratch((bk, d)), _scratch((bk, d))],
        interpret=interpret,
        **_compiler_params(
            interpret, ("parallel", "parallel", "parallel", "arbitrary")
        ),
    )(qt, kt, vt, ot, dot, lse, *extra, *seg_inputs)

    if n_rep > 1:
        dk = dk.reshape(b, hk, n_rep, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, n_rep, sk, d).sum(axis=2)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3).astype(k.dtype),
        dv.transpose(0, 2, 1, 3).astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention(q, k, v, segment_ids, causal, softmax_scale, block_q,
                     block_k):
    interpret = _platform() not in ("tpu", "axon")
    return _flash_fwd(
        q,
        k,
        v,
        causal=causal,
        softmax_scale=softmax_scale,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        segment_ids=segment_ids,
    )


def _block_override(name: str, value: int, seq: int,
                    align: int) -> Optional[int]:
    """Validate a KFT_FLASH_BLOCK_* env override against the kernel's
    divisibility rules (the same ones ``supported()`` enforces for the
    floor blocks).  An ALIGNMENT violation is a typo that can never be
    legal for any shape — raise so a sweep fails loudly instead of
    silently benchmarking the fallback.  A sequence the override does not
    divide returns None (use the heuristic for that call): the override
    is process-global while ``impl="auto"`` may route OTHER shapes (a
    serve prefill, an eval pass) through the kernel in the same process,
    and those must not crash on the sweep's knob."""
    if value <= 0 or value % align != 0:
        raise ValueError(
            f"{name}={value} is not a positive multiple of {align} "
            f"(TPU {'sublane' if align == 8 else 'lane'} alignment)"
        )
    if seq % value != 0:
        return None
    return value


def default_blocks(sq: int, sk: int) -> tuple:
    """Measured block-size heuristic (v5e block study, BASELINE.md): bigger
    tiles amortize per-grid-cell overhead as sequence grows — 2.3x faster
    at seq 8192 with 1024x1024 vs the 256x256 floor — until VMEM bounds
    them (2048 tiles fail to compile at d=128).  Ragged lengths fall back
    to the floor, which divides everything supported() admits.

    ``KFT_FLASH_BLOCK_Q`` / ``KFT_FLASH_BLOCK_K`` override the heuristic
    per process (block sweeps without code edits); overrides are validated
    against the kernel's alignment rules (raise on an always-illegal
    size) and fall back to the heuristic for sequences they do not
    divide — the override is process-global and must not crash other
    auto-routed shapes."""
    from kubeflow_tpu.platform import config

    env_q = config.env_int("KFT_FLASH_BLOCK_Q", 0)
    env_k = config.env_int("KFT_FLASH_BLOCK_K", 0)
    bq = _block_override("KFT_FLASH_BLOCK_Q", env_q, sq, 8) if env_q else None
    if bq is None:
        bq = min(1024, max(DEFAULT_BLOCK_Q, (sq // 8) // 8 * 8))
        if sq % bq:
            bq = min(DEFAULT_BLOCK_Q, sq)
    bk = _block_override("KFT_FLASH_BLOCK_K", env_k, sk, 128) if env_k else None
    if bk is None:
        bk = min(1024, max(DEFAULT_BLOCK_K, (sk // 8) // 128 * 128))
        if sk % bk:
            bk = min(DEFAULT_BLOCK_K, sk)
    return bq, bk


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Flash attention, BSHD layout, GQA via fewer kv heads.  Block sizes
    default to the measured sequence-length heuristic (default_blocks).
    ``segment_ids`` ([b, s] int, 0 = pad) masks cross-document attention
    for packed sequences; causal cross-length shapes (sq < sk) use the
    end-aligned offset convention (see ``supported``)."""
    if block_q is None or block_k is None:
        auto_q, auto_k = default_blocks(q.shape[1], k.shape[1])
        block_q = auto_q if block_q is None else block_q
        block_k = auto_k if block_k is None else block_k
    return _flash_attention(q, k, v, segment_ids, causal, softmax_scale,
                            block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_with_lse(q, k, v, causal, softmax_scale, block_q,
                              block_k):
    interpret = _platform() not in ("tpu", "axon")
    return _flash_fwd(
        q, k, v, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        return_residuals=True,
    )


def flash_attention_with_lse(
    q, k, v, *, causal: bool = False,
    softmax_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Flash attention that also returns the per-row logsumexp
    (lane-replicated [b, h, sq, 128] f32) — the residual block-merging
    consumers need (ring attention's cross-device flash merge).  Fully
    differentiable including the lse output."""
    if block_q is None or block_k is None:
        auto_q, auto_k = default_blocks(q.shape[1], k.shape[1])
        block_q = auto_q if block_q is None else block_q
        block_k = auto_k if block_k is None else block_k
    return _flash_attention_with_lse(
        q, k, v, causal, softmax_scale, block_q, block_k
    )


def _with_lse_fwd(q, k, v, causal, softmax_scale, block_q, block_k):
    out, lse = _flash_attention_with_lse(
        q, k, v, causal, softmax_scale, block_q, block_k
    )
    return (out, lse), (q, k, v, out, lse)


def _with_lse_bwd(causal, softmax_scale, block_q, block_k, res, cotangents):
    q, k, v, out, lse = res
    g_out, g_lse = cotangents
    interpret = _platform() not in ("tpu", "axon")
    return _flash_bwd(
        q, k, v, out, lse, g_out, causal=causal,
        softmax_scale=softmax_scale, block_q=block_q, block_k=block_k,
        interpret=interpret, g_lse=g_lse.astype(jnp.float32),
    )


_flash_attention_with_lse.defvjp(_with_lse_fwd, _with_lse_bwd)


def _vjp_fwd(q, k, v, segment_ids, causal, softmax_scale, block_q, block_k):
    # Under differentiation the forward additionally emits the per-row
    # logsumexp — the only residual the blocked backward needs beyond the
    # inputs and output (recomputing P per tile, FlashAttention-2 style).
    interpret = _platform() not in ("tpu", "axon")
    out, lse = _flash_fwd(
        q, k, v, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        return_residuals=True, segment_ids=segment_ids,
    )
    return out, (q, k, v, segment_ids, out, lse)


def _vjp_bwd(causal, softmax_scale, block_q, block_k, res, g):
    q, k, v, segment_ids, out, lse = res
    interpret = _platform() not in ("tpu", "axon")
    dq, dk, dv = _flash_bwd(
        q, k, v, out, lse, g, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        segment_ids=segment_ids,
    )
    # segment_ids are integral — no cotangent.
    return dq, dk, dv, None


_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
