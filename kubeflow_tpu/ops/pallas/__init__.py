"""Pallas TPU kernels.  Import lazily; everything has an XLA fallback."""
