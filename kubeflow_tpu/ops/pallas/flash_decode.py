"""Single-token decode attention kernel for TPU (Pallas/Mosaic).

Decode attention (one query token against an S-slot KV cache) is the
bandwidth-bound inner loop of generation: per generated token every layer
streams its whole cache from HBM.  XLA's unfused path materializes the
[b, h, S] logits to HBM and reads the cache a second time for the
softmax·V contraction; this kernel folds the whole thing into one pass
with an online-softmax accumulator, so HBM sees each cache byte exactly
once and the logits never leave VMEM.

Layout matters more than FLOPs here: the kernel wants (d, S)-transposed
per-head tiles (``flash_decode_ds``) so the long S axis sits on the
128-lane minor dimension at full density; (bk, d=64) tiles lane-pad
64→128 and double the DMA bytes.  Three layouts were measured end to end
on the tunneled v5e (BASELINE.md decode-kernel log) and ALL lost to XLA's
decode there — per-grid-cell overhead on tiny GQA tiles dominates and the
chip's achievable bandwidth leaves no single-pass headroom — so the model
cache stays sequence-major ([b, S, kv_h, d], layers.py `_update_cache`)
and this kernel is opt-in (KUBEFLOW_TPU_FORCE_FLASH_DECODE=1) via the
transposing `flash_decode` wrapper, kept correctness-tested for
full-bandwidth hardware where the single-pass math wins.

* The q "tile" is the GQA group — all ``g = h / kv_h`` query heads that
  share one kv head.  For MHA g=1 the score product is a skinny matvec;
  fine — this kernel is HBM-bound, not MXU-bound.
* The additive bias row ([b, S]: padding slots + unwritten slots at
  -1e30) rides the same grid, replicated to 8 sublanes for Mosaic tiling.
* kv blocks ride the innermost (sequential) grid axis; (m, l, acc)
  scratch carries across it, like the training kernel
  (flash_attention.py).

No backward: decode is inference-only.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kubeflow_tpu.ops.pallas.flash_attention import (
    _compiler_params as _fa_compiler_params,
    _platform,
    _scratch,
    pltpu,
)

_NEG_INF = -1e30
DEFAULT_BLOCK_K = 512


def force_enabled() -> bool:
    """Test/debug override: use the kernel (interpret mode off-TPU) even
    where the platform gate would fall back to XLA."""
    from kubeflow_tpu.platform import config

    return config.knob("KUBEFLOW_TPU_FORCE_FLASH_DECODE", "",
                       doc="'1' forces the flash-decode kernel "
                           "(interpret mode off-TPU)") == "1"


def _pick_block(S: int) -> Optional[int]:
    for bk in (DEFAULT_BLOCK_K, 256, 128):
        if S % bk == 0:
            return bk
    return None


def supported(q, k, v, *, bias_rows=None, ds_major=False) -> bool:
    """Shape gate; the caller falls back to XLA when False.

    ``ds_major=True`` checks k/v as [b, kv_h, d, S] (the model cache
    layout), else [b, S, kv_h, d]."""
    if pltpu is None:
        return False
    b, s, h, d = q.shape
    if ds_major:
        bk_, kv_h, dk, S = k.shape
    else:
        bk_, S, kv_h, dk = k.shape
    if s != 1 or bk_ != b or v.shape != k.shape or d != dk:
        return False
    if h % kv_h != 0:
        return False
    if d % 8 != 0 or d > 256:
        return False
    if bias_rows is not None and bias_rows.shape != (b, S):
        return False
    return _pick_block(S) is not None


def _decode_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, num_k):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)        # (g, d)
    k = k_ref[0, 0].astype(jnp.float32)        # (d, bk) — dS-major tile
    s = jax.lax.dot_general(
        q, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                   # (g, bk)
    s = s + bias_ref[0, 0][None, :]             # (bk,) broadcast over g

    m_prev = m_ref[...]                         # (g, 128) lane-replicated
    row_max = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, row_max)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, 0:1])
    l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    # PV: p (g, bk) × v (d, bk) contracted over bk → (g, d).
    acc_ref[...] = acc_ref[...] * alpha[:, 0:1] + jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0, 0],
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )

    @pl.when(ki == num_k - 1)
    def _finish():
        l = l_ref[...][:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _compiler_params(interpret):
    return _fa_compiler_params(
        interpret, ("parallel", "parallel", "arbitrary")
    )


def flash_decode_ds(
    q, k_ds, v_ds, bias_rows=None, *,
    softmax_scale: Optional[float] = None,
    block_k: Optional[int] = None,
):
    """Decode attention over a dS-MAJOR cache: q [b, 1, h, d],
    k/v [b, kv_h, d, S], optional additive bias row [b, S].
    Returns [b, 1, h, d].

    (d, S) per-head tiles put the long S axis on the 128-lane minor
    dimension, so a (d=64, bk) block is fully dense — a (bk, d=64) layout
    would lane-pad 64→128 and double the DMA bytes, which measured SLOWER
    than XLA end to end."""
    b, s, h, d = q.shape
    _, kv_h, _, S = k_ds.shape
    if s != 1:
        raise ValueError(f"flash_decode is single-token only, got s={s}")
    g = h // kv_h
    bk = block_k or _pick_block(S)
    if bk is None or S % bk:
        raise ValueError(f"cache length {S} has no supported block size")
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    if bias_rows is None:
        bias_rows = jnp.zeros((b, S), jnp.float32)
    # Mosaic wants >= (8, 128) tiles: replicate the bias row across 8
    # sublanes (a few extra KB per step vs the cache's GBs — noise).
    bias8 = jnp.broadcast_to(
        bias_rows.astype(jnp.float32)[:, None, :], (b, 8, S)
    )
    # GQA grouping: consecutive q heads share a kv head (q head j ↔ kv head
    # j // g — the training kernel's hi // n_rep convention).
    qg = q[:, 0].reshape(b, kv_h, g, d)
    num_k = S // bk
    interpret = _platform() not in ("tpu", "axon")

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, num_k=num_k),
        grid=(b, kv_h, num_k),
        in_specs=[
            pl.BlockSpec((1, 1, g, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, d, bk), lambda bi, hi, ki: (bi, hi, 0, ki)),
            pl.BlockSpec((1, 1, d, bk), lambda bi, hi, ki: (bi, hi, 0, ki)),
            pl.BlockSpec((1, 8, bk), lambda bi, hi, ki: (bi, 0, ki)),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kv_h, g, d), q.dtype),
        scratch_shapes=[
            _scratch((g, d)),     # acc
            _scratch((g, 128)),   # m
            _scratch((g, 128)),   # l
        ],
        interpret=interpret,
        **_compiler_params(interpret),
    )(qg, k_ds, v_ds, bias8)
    return out.reshape(b, h, d)[:, None]


def flash_decode(
    q, k, v, bias_rows=None, *,
    softmax_scale: Optional[float] = None,
    block_k: Optional[int] = None,
):
    """Decode attention, sequence-major cache k/v [b, S, kv_h, d] — the
    model cache layout; inputs are transposed to the kernel's dS-major
    tiles on entry.  Callers that already hold a dS-major cache can use
    ``flash_decode_ds`` directly."""
    return flash_decode_ds(
        q, k.transpose(0, 2, 3, 1), v.transpose(0, 2, 3, 1), bias_rows,
        softmax_scale=softmax_scale, block_k=block_k,
    )
