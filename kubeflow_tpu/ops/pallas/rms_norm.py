"""Pallas TPU kernel for RMSNorm (every Llama layer runs it twice).

Forward: rows are tiled into (block_rows, d) VMEM blocks; the f32
mean-square, rsqrt, and scale all happen in one VPU pass per tile, so x is
read from HBM exactly once and y written once — the op is bandwidth-bound,
and this is its bandwidth floor.  XLA usually fuses the surrounding
elementwise chain to the same effect (ops/norms.py keeps XLA as the
default); the kernel exists for the residual cases where the fusion breaks
(measured via ops.norms.rms_norm(impl=...), not assumed).

Backward: analytic VJP in plain XLA (two reductions) — a Pallas backward
would only re-derive the same bandwidth floor.

On non-TPU backends the kernel runs in interpret mode (CPU test suite);
``supported`` gates shapes: last dim must be lane-aligned (%128).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas extras are unavailable on pure-CPU builds.
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
except ImportError:  # pragma: no cover
    pltpu = None

# Target ~1 MiB of f32 per input tile; sublane-aligned (multiple of 8).
_TARGET_TILE_BYTES = 1 << 20


def _platform() -> str:
    return jax.devices()[0].platform


def supported(x: jax.Array) -> bool:
    if pltpu is None:
        return False
    d = x.shape[-1]
    return d % 128 == 0 and x.size // d >= 1


def _block_rows(n_rows: int, d: int) -> int:
    rows = max(8, _TARGET_TILE_BYTES // (4 * d))
    rows = (rows // 8) * 8
    # Blocks stay sublane-aligned (multiple of 8) even when n_rows is
    # small/odd; _forward pads the rows up to the block multiple.
    return min(rows, ((n_rows + 7) // 8) * 8)


def _kernel(x_ref, scale_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * scale_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_2d(x, scale, eps):
    return _forward(x, scale, eps)


def _forward(x, scale, eps):
    n, d = x.shape
    block = _block_rows(n, d)
    pad = (-n) % block
    if pad:
        x_in = jnp.pad(x, ((0, pad), (0, 0)))
    else:
        x_in = x
    out = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=((n + pad) // block,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x_in.shape, x.dtype),
        interpret=_platform() != "tpu",
    )(x_in, scale)
    return out[:n] if pad else out


def _fwd(x, scale, eps):
    return _forward(x, scale, eps), (x, scale)


def _bwd(eps, res, g):
    x, scale = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    s32 = scale.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    gs = g32 * s32
    dx = r * gs - x32 * (r**3) * jnp.mean(gs * x32, axis=-1, keepdims=True)
    dscale = jnp.sum(g32 * x32 * r, axis=0)
    return dx.astype(x.dtype), dscale.astype(scale.dtype)


_rms_norm_2d.defvjp(_fwd, _bwd)


def rms_norm(x: jax.Array, scale: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    """Pallas RMSNorm over the last axis; leading axes are flattened into
    rows.  Differentiable (custom VJP)."""
    d = x.shape[-1]
    y = _rms_norm_2d(x.reshape(-1, d), scale, eps)
    return y.reshape(x.shape)
