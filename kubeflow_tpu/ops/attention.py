"""Multi-head attention with a Pallas TPU flash kernel and an XLA fallback.

All shapes are ``[batch, seq, heads, head_dim]`` (BSHD — the layout XLA:TPU
prefers for fusing the surrounding projections).  GQA is supported by passing
k/v with fewer heads; they are logically repeated.

The reference platform contains no attention code at all (SURVEY.md §2.13) —
long-context support there is "whatever the user runs inside the notebook".
Here it is a first-class op: ``impl="pallas"`` selects the flash kernel
(ops/pallas/flash_attention.py), and ring-attention context parallelism
builds on this op in ``kubeflow_tpu.parallel.ring``.

Masking is allocation-free on every path.  The XLA fallback builds its
causal condition from a ``broadcasted_iota`` row/col comparison fused
straight into the ``jnp.where`` — no ``jnp.tril(jnp.ones(...))`` bool
buffer (the exact BENCH_r05 RESOURCE_EXHAUSTED allocation, which
materialized eagerly during ``model.init`` outside any jit) — and folds
segment-id equality into the same fused select.  The flash kernel never
materializes the [Sq, Sk] plane at all.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def attention_footprint_bytes(*, batch: int, heads: int, q_len: int,
                              k_len: int, causal: bool,
                              segments: bool) -> int:
    """O(S²) bytes the masked XLA path materializes, from shapes alone:
    the f32 logits AND softmax probs ([b, h, sq, sk] each — softmax
    computes in f32 before the value-matmul cast).  The masks themselves
    no longer count: both the causal condition and the segment-id
    equality are iota/compare ops fused into the select, so no standalone
    mask buffer exists (``causal``/``segments`` stay in the signature for
    the telemetry attrs and future per-variant accounting).  Computed at
    trace time, strictly before XLA allocates any of it.

    Scope: this is the JIT-regime footprint (every production path — the
    train step and now ``create_train_state``'s jitted init — runs under
    jit, where the select condition fuses to zero bytes).  A bare eager
    call additionally holds the transient bool condition (sq·sk, plus
    b·sq·sk with segments) while the select executes — O(S²)/4 of the
    logits term, and still far below the old ones+tril+segment buffers."""
    del causal, segments  # mask-free: neither adds a materialized buffer
    return 2 * 4 * batch * heads * q_len * k_len  # f32 logits + probs


def _preflight_mask_check(q: jax.Array, k: jax.Array, *, causal: bool,
                          segments: bool) -> None:
    """Publish the footprint estimate + budget warning (telemetry.compute)
    for a masked attention call.  Runs under jit TRACING — shapes are
    static Python ints and the gauge/warning fire before any allocation
    attempt, which is the whole point: the BENCH_r05 RESOURCE_EXHAUSTED
    becomes a watched signal, not a post-mortem."""
    from kubeflow_tpu.telemetry import compute as ctel

    est = attention_footprint_bytes(
        batch=q.shape[0], heads=q.shape[2], q_len=q.shape[1],
        k_len=k.shape[1], causal=causal, segments=segments,
    )
    ctel.note_attention_estimate(
        est, batch=q.shape[0], heads=q.shape[2], q_len=q.shape[1],
        k_len=k.shape[1], causal=causal, segments=segments, impl="xla",
    )


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference implementation; XLA fuses this well enough for short seqs.

    Masking is mask-free: the causal condition is an iota comparison and
    the segment condition an equality compare, both fused by XLA into the
    single ``jnp.where`` select over the logits — no [sq, sk] boolean
    buffer is ever a standalone allocation (regression-pinned by
    tests/test_attention.py's jaxpr inspection)."""
    orig_dtype = q.dtype
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    if causal or segment_ids is not None:
        # Pre-flight BEFORE building logits: estimate the O(S²) footprint
        # from static shapes and warn when it won't fit the HBM budget
        # (telemetry.compute) — the BENCH_r05 crash mode.
        _preflight_mask_check(
            q, k, causal=causal, segments=segment_ids is not None)
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5

    # [b, h, sq, sk] logits in f32 for a stable softmax.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    cond = None
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # End-aligned (offset sk - sq, the old tril(k=sk-sq) convention —
        # supports cross-ring blocks where q starts later than k).  The
        # iotas are O(S) column/row VECTORS broadcast by the compare: under
        # jit everything fuses into the select (zero mask buffers); even
        # eagerly the only transient is the bool condition the select
        # needs anyway — never an O(S²) int32 or f32 ones/tril buffer.
        rows = jax.lax.broadcasted_iota(jnp.int32, (1, 1, sq, 1), 2)
        cols = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 1, sk), 3)
        cond = (rows + (sk - sq)) >= cols
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        cond = seg if cond is None else jnp.logical_and(cond, seg)
    if cond is not None:
        logits = jnp.where(cond, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return out.astype(orig_dtype)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",
) -> jax.Array:
    """Scaled dot-product attention, BSHD layout.

    impl: "auto" | "pallas" | "xla" | "ring" | "ulysses".  "auto" prefers
    the Pallas flash kernel on TPU for bias-free shapes it supports
    (including packed ``segment_ids`` and causal sq<sk), else falls back
    to XLA.  "ring" runs sequence-parallel ring attention over the active
    mesh's ``sp`` axis (kubeflow_tpu.parallel.ring); "ulysses" re-shards
    head↔sequence with all-to-alls instead (kubeflow_tpu.parallel.ulysses)
    — better when heads divide the axis and per-device sequence fits HBM.

    The selected implementation is recorded at trace time in
    ``attention_kernel_calls_total{impl}`` (telemetry.compute) — the
    signal ci/bench_smoke.py uses to prove the flash arm really ran the
    Pallas kernel rather than silently falling back.
    """
    if impl not in ("auto", "pallas", "xla", "ring", "ulysses"):
        raise ValueError(f"unknown impl {impl!r}")
    from kubeflow_tpu.telemetry import compute as ctel

    if impl in ("ring", "ulysses"):
        from kubeflow_tpu.parallel.context import get_global_mesh

        mesh = get_global_mesh()
        if mesh is None:
            raise RuntimeError(
                f"impl={impl!r} needs an active mesh; wrap the call in "
                "kubeflow_tpu.parallel.context.global_mesh(mesh)"
            )
        if bias is not None or segment_ids is not None:
            raise NotImplementedError(f"{impl} attention: bias/segment_ids TODO")
        ctel.note_attention_impl(impl)
        if impl == "ring":
            from kubeflow_tpu.parallel.ring import ring_attention

            return ring_attention(
                q, k, v, mesh=mesh, causal=causal, softmax_scale=softmax_scale
            )
        from kubeflow_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, mesh=mesh, causal=causal, softmax_scale=softmax_scale
        )

    use_pallas = False
    if impl in ("auto", "pallas"):
        from kubeflow_tpu.ops.pallas import flash_attention as fa

        ok = fa.supported(q, k, v, bias=bias, segment_ids=segment_ids,
                          causal=causal)
        if impl == "pallas" and not ok:
            raise ValueError("pallas flash attention does not support this shape")
        use_pallas = ok and (
            impl == "pallas"
            or fa.should_use(q, k, causal=causal,
                             segments=segment_ids is not None)
        )
    if use_pallas:
        from kubeflow_tpu.ops.pallas import flash_attention as fa

        ctel.note_attention_impl("pallas")
        return fa.flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            softmax_scale=softmax_scale
        )
    ctel.note_attention_impl("xla")
    return xla_attention(
        q,
        k,
        v,
        causal=causal,
        segment_ids=segment_ids,
        bias=bias,
        softmax_scale=softmax_scale,
    )
