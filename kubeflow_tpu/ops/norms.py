"""Normalization ops.  RMSNorm is the hot one (every Llama layer, twice)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, *, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with f32 accumulation, output in x.dtype.

    XLA fuses this into neighbouring ops on TPU; a Pallas version exists in
    ops/pallas for the cases where it doesn't (measured, not assumed).
    """
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(orig_dtype)
