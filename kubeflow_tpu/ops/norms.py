"""Normalization ops.  RMSNorm is the hot one (every Llama layer, twice)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, *, eps: float = 1e-6,
             impl: str = "auto") -> jax.Array:
    """RMSNorm with f32 accumulation, output in x.dtype.

    impl: "auto" | "xla" | "pallas".  XLA fuses this into neighbouring ops
    on TPU, so "auto" stays on XLA; "pallas" selects the single-pass VMEM
    kernel (ops/pallas/rms_norm.py) for the cases where the fusion breaks —
    choose by measuring, not assuming.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown impl {impl!r}")
    if impl == "pallas":
        from kubeflow_tpu.ops.pallas import rms_norm as pallas_rms

        if pallas_rms.pltpu is None:
            raise ValueError(
                "pallas rms_norm unavailable: jax.experimental.pallas.tpu "
                "is not importable in this JAX build"
            )
        if not pallas_rms.supported(x):
            raise ValueError(
                f"pallas rms_norm needs a %128 last dim, got {x.shape}"
            )
        return pallas_rms.rms_norm(x, scale, eps=eps)
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(orig_dtype)
