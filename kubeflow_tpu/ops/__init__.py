"""TPU op library: Pallas kernels with XLA reference fallbacks.

Public API is stable regardless of backend: ``impl="auto"`` uses the Pallas
TPU kernel when it applies and falls back to the pure-XLA reference
otherwise (CPU tests, odd shapes).
"""

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.norms import rms_norm

__all__ = ["dot_product_attention", "rms_norm"]
