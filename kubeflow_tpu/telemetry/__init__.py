"""Shared telemetry core for BOTH halves of the repo.

The control plane (platform/runtime) and the compute plane
(train/models/ops) export spans, histograms, and gauges through one
implementation:

* ``telemetry.trace`` — the Tracer (thread-carried traces, ring buffer,
  slow-trace JSON dumps); ``platform/runtime/trace.py`` wraps one
  instance in the PR-1 module API, ``telemetry.compute``/``serve`` own
  their own.
* ``telemetry.metrics`` — registry hygiene + histogram quantile
  estimation (the bench/report seam).
* ``telemetry.compute`` — step timing, MFU/throughput accounting, HBM
  watermarks, the attention allocation pre-flight.
* ``telemetry.serve`` — per-request serve metrics and spans.

``logfmt`` is the shared structured-line formatter: machine-parseable
``event key=value`` lines for everything that isn't a JSON span dump
(train-loop progress lines, operator greps).
"""
from __future__ import annotations

from kubeflow_tpu.telemetry.trace import Span, Trace, Tracer  # noqa: F401


def logfmt(event: str, **fields) -> str:
    """``event key=value ...`` with floats at %.6g — one line, no spaces
    inside values' numeric forms, parseable by ``dict(kv.split("="))``."""
    parts = [event]
    for k, v in fields.items():
        if isinstance(v, float):
            parts.append(f"{k}={v:.6g}")
        else:
            parts.append(f"{k}={v}")
    return " ".join(parts)
