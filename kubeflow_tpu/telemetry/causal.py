"""Causal trace context for the control plane: W3C-style traceparent
propagation through OBJECT WRITES and WATCH EVENTS.

The per-reconcile Tracer (telemetry/trace.py) answers "where did THIS
reconcile go?"; it dies at every process and thread boundary, so nobody
can answer "where did the 2.2 ms/notebook actually go — watch lag, queue
wait, reconcile CPU, or write RTT?".  In an RPC system the answer is
Dapper/OpenTelemetry context propagation down the call stack; in a
reconcile-driven system causality flows through the API server — a write
causes a watch delivery causes an enqueue causes a reconcile causes more
writes — so the context must ride the OBJECTS themselves:

* **mint** — a 128-bit ``trace_id`` + 64-bit ``span_id`` is minted at
  first admission (CRD create through any client, a web backend POST, a
  serve request's incoming header) and stamped into the
  ``kubeflow.org/traceparent`` annotation (W3C traceparent syntax) with
  the stamp wall time in ``kubeflow.org/tracestate`` (``kft=ts:<epoch>``
  — what watch-lag is measured against);
* **stamp** — ``runtime/apply.py`` stamps every child object a
  reconciler generates with a child context of the reconcile's own
  (same trace_id, fresh span_id): a notebook's StatefulSets, a TPUJob's
  gang, an InferenceService's revision Deployments all join the parent's
  journey;
* **extract** — controllers re-extract the context at watch delivery and
  carry it through the workqueue to the reconcile, where it becomes the
  thread-local *current* context (and rides FlightPool fan-outs exactly
  like the write-fence context);
* **link** — the reconcile's Tracer trace carries
  ``causal_trace_id``/``causal_span_id``, so ``/debug/traces?trace_id=``
  finds every reconcile of a journey.

Spans land in a bounded per-process store (``record``/``journey``,
served at ``/debug/journey/<trace_id>``); per-replica stores from a
sharded fleet join with ``merge_journeys``.  The segment names the
critical-path analyzer (telemetry/critical_path.py) decomposes a journey
into are the ``segment=`` values recorded here: ``watch_lag``,
``queue_wait``, ``reconcile``, ``write_rtt``, ``pod_start``,
``admission_queue``, ``readiness_warm``.

Id minting keeps the PR-2 "no urandom per reconcile" property via a
counter-in-random-block scheme: ONE ``secrets`` read per process seeds a
random 128-bit block, and each id is the block plus an incrementing
counter — unique within a process by the counter, unique across replicas
by the per-process entropy (the PR-1 16-hex prefix+counter ids could
collide across sharded replicas in a merged journey; these cannot,
pinned in test_sharding.py).
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
import re
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from kubeflow_tpu.platform import config

TRACEPARENT_ANNOTATION = "kubeflow.org/traceparent"
TRACESTATE_ANNOTATION = "kubeflow.org/tracestate"
TRACEPARENT_HEADER = "traceparent"

# Objects minted at first admission when they arrive context-free: the
# platform's own API group (a Notebook, TPUJob, InferenceService ... CR
# is a journey ROOT; core-kind children are stamped explicitly by
# apply.* from their parent's context instead).
MINT_API_GROUP = "kubeflow.org"

# Bounded per-process span store (the /debug/journey body).
JOURNEY_BUFFER_SIZE = config.knob(
    "JOURNEY_BUFFER_SIZE", 8192, int,
    doc="causal span store size (spans, process-wide ring)")
# Watch-lag spans older than this are informer replays of objects stamped
# long before this journey window (add_handler ADDED replays, relists) —
# recording them would graft minutes-long phantom segments onto the
# journey.
WATCH_LAG_MAX_S = config.knob(
    "JOURNEY_WATCH_LAG_MAX_SECONDS", 60.0, float,
    doc="watch_lag spans longer than this are dropped as replays")
ENABLED = not config.env_bool("JOURNEY_DISABLE", False)

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")
_TS_RE = re.compile(r"kft=ts:([0-9.]+)")

# -- id minting (counter-in-random-block; one secrets read per process) -------

_rand = secrets.token_bytes(24)
_trace_base = int.from_bytes(_rand[:16], "big")
_span_base = int.from_bytes(_rand[16:], "big")
_counter = itertools.count()


def new_trace_id() -> str:
    """128-bit trace id: per-process random block + counter.  The high
    64 bits stay pure per-process entropy, so ids from different replicas
    never collide in a merged journey; the counter makes in-process ids
    unique without a syscall per trace."""
    return f"{(_trace_base + next(_counter)) & ((1 << 128) - 1):032x}"


def new_span_id() -> str:
    return f"{(_span_base + next(_counter)) & ((1 << 64) - 1):016x}"


# -- context ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraceContext:
    trace_id: str
    span_id: str
    # Wall time the context was stamped onto its object (from the
    # tracestate annotation) — what watch_lag measures from.  None for
    # contexts that never rode an object (serve headers).
    stamped_ts: Optional[float] = None

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def mint() -> TraceContext:
    return TraceContext(new_trace_id(), new_span_id())


def child(ctx: TraceContext) -> TraceContext:
    """Same trace, fresh span id — the link from a cause (the stamped
    parent / the delivering event) to its effect (a reconcile, a child
    write)."""
    return TraceContext(ctx.trace_id, new_span_id())


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    return TraceContext(m.group(1), m.group(2))


# -- thread-local current context --------------------------------------------

_local = threading.local()


def current() -> Optional[TraceContext]:
    """The thread's current context.  A lazy factory (set_lazy) resolves
    on FIRST use here: a steady-state no-op reconcile that never writes
    never pays for deriving its context (the resync allocation band)."""
    ctx = getattr(_local, "ctx", None)
    if ctx is not None:
        return ctx
    factory = getattr(_local, "ctx_factory", None)
    if factory is not None:
        _local.ctx_factory = None  # one shot, even when it answers None
        ctx = factory()
        _local.ctx = ctx
    return ctx


def current_resolved() -> Optional[TraceContext]:
    """The current context ONLY if already resolved — never triggers a
    lazy factory (the controller's post-reconcile check: did anything
    actually use the context?)."""
    return getattr(_local, "ctx", None)


def set_current(ctx: Optional[TraceContext]) -> None:
    _local.ctx = ctx
    _local.ctx_factory = None


def set_lazy(factory) -> None:
    """Install a zero-argument context factory resolved on first
    ``current()`` call (a write, a child stamp) — the allocation-free
    path for reconciles that may turn out to be no-ops."""
    _local.ctx = None
    _local.ctx_factory = factory


def current_traceparent() -> Optional[str]:
    ctx = current()
    return ctx.to_traceparent() if ctx is not None else None


@contextmanager
def use(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the current context for the block (no-op on
    None, so callers can wrap unconditionally)."""
    if ctx is None:
        yield None
        return
    prev = current()
    set_current(ctx)
    try:
        yield ctx
    finally:
        set_current(prev)


# -- the object annotation contract ------------------------------------------


def _annotations(obj) -> Dict:
    md = obj.get("metadata") if hasattr(obj, "get") else None
    if md is None:
        return {}
    return md.get("annotations") or {}


def from_object(obj) -> Optional[TraceContext]:
    """Extract the context an object carries (watch delivery / cache
    read); accepts frozen informer views — reads only."""
    ann = _annotations(obj)
    ctx = parse_traceparent(ann.get(TRACEPARENT_ANNOTATION))
    if ctx is None:
        return None
    m = _TS_RE.search(ann.get(TRACESTATE_ANNOTATION) or "")
    if m is not None:
        try:
            return dataclasses.replace(ctx, stamped_ts=float(m.group(1)))
        except ValueError:
            pass
    return ctx


def stamp(obj, ctx: Optional[TraceContext] = None) -> Optional[TraceContext]:
    """Write ``ctx`` (default: a fresh mint) into the object's
    annotations with the stamp wall time.  Returns the stamped context;
    None when the object is immutable (a frozen view — the caller is
    serializing a cache read, not authoring a write)."""
    if ctx is None:
        ctx = mint()
    ctx = dataclasses.replace(ctx, stamped_ts=round(time.time(), 6))
    try:
        ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
        ann[TRACEPARENT_ANNOTATION] = ctx.to_traceparent()
        ann[TRACESTATE_ANNOTATION] = f"kft=ts:{ctx.stamped_ts}"
    except (TypeError, AttributeError):
        return None
    return ctx


def stamp_child(obj) -> Optional[TraceContext]:
    """Stamp a reconciler-generated child object: a child context of the
    current (reconcile) context when one is installed, else the
    first-admission mint rule.  The apply.* helpers call this on every
    create/update they author — a raw ``client.create`` that skips it
    severs the journey silently (kftlint R009)."""
    cur = current()
    if cur is not None:
        return stamp(obj, child(cur))
    return mint_on_admission(obj)


def mint_on_admission(obj) -> Optional[TraceContext]:
    """First-admission minting, shared by every client CREATE path
    (RestKubeClient, FakeKube, and therefore HttpKube): an object already
    carrying a context keeps it; a context-free object of the platform's
    API group is stamped from the caller's current context (a CRUD
    backend request, an upstream traceparent header) or a fresh mint.
    Other groups pass through untouched — their stamps come from apply.*
    with a real parent."""
    existing = from_object(obj)
    if existing is not None:
        return existing
    api = obj.get("apiVersion", "") if hasattr(obj, "get") else ""
    if not str(api).startswith(MINT_API_GROUP + "/"):
        return None
    cur = current()
    return stamp(obj, child(cur) if cur is not None else mint())


def stamped_copy_on_admission(obj):
    """``mint_on_admission`` for callers that must not mutate their
    input (RestKubeClient serializing a caller-owned dict or a frozen
    view): returns the object unchanged when no mint applies, else a
    SHALLOW copy with copied metadata/annotations carrying the stamp —
    the caller's object is never touched, matching FakeKube's
    stamp-after-copy behavior."""
    if from_object(obj) is not None:
        return obj
    api = obj.get("apiVersion", "") if hasattr(obj, "get") else ""
    if not str(api).startswith(MINT_API_GROUP + "/"):
        return obj
    out = dict(obj)
    md = dict(out.get("metadata") or {})
    md["annotations"] = dict(md.get("annotations") or {})
    out["metadata"] = md
    cur = current()
    stamp(out, child(cur) if cur is not None else mint())
    return out


def annotations_of(obj) -> Dict[str, str]:
    """The two causal annotations an object carries (for patches that
    must restamp alongside the generated-hash annotation)."""
    ann = _annotations(obj)
    return {k: ann[k] for k in (TRACEPARENT_ANNOTATION,
                                TRACESTATE_ANNOTATION) if k in ann}


# -- the span store -----------------------------------------------------------


class SpanStore:
    """Bounded per-process store of causal spans, keyed by nothing —
    journeys are reconstructed by trace_id scan over the ring (the ring
    is small; a scan is cheaper than maintaining an index that must
    evict in lockstep)."""

    def __init__(self, maxlen: int = JOURNEY_BUFFER_SIZE):
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque(
            maxlen=max(int(maxlen), 16))

    def record(self, name: str, *, trace_id: str,
               span_id: Optional[str] = None,
               parent_span_id: Optional[str] = None,
               segment: Optional[str] = None,
               start_ts: float, end_ts: float, **attrs) -> dict:
        span = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id or new_span_id(),
            "start_ts": round(start_ts, 6),
            "end_ts": round(end_ts, 6),
            "duration_ms": round(max(end_ts - start_ts, 0.0) * 1e3, 3),
        }
        if parent_span_id:
            span["parent_span_id"] = parent_span_id
        if segment:
            span["segment"] = segment
        if attrs:
            span.update(attrs)
        with self._lock:
            self._spans.append(span)
        return span

    def journey(self, trace_id: str) -> List[dict]:
        with self._lock:
            spans = [dict(s) for s in self._spans
                     if s["trace_id"] == trace_id]
        spans.sort(key=lambda s: (s["start_ts"], s["end_ts"]))
        return spans

    def recent(self, *, start: float = float("-inf"),
               end: float = float("inf")) -> List[dict]:
        """Copies of every stored span whose end lands in
        ``[start, end]``, time-ordered — the incident flight recorder's
        worst-journey scan (telemetry/incidents.py) and any other reader
        that needs the ring without knowing trace ids up front."""
        with self._lock:
            spans = [dict(s) for s in self._spans
                     if start <= s["end_ts"] <= end]
        spans.sort(key=lambda s: (s["start_ts"], s["end_ts"]))
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


STORE = SpanStore()

# Stamps whose watch_lag span was already recorded IN THIS PROCESS —
# shared across controllers and in-process replicas (a ShardedFleet
# handover must not re-record a stamp the dead replica already
# measured; merge_journeys dedupes span ids, not semantics).  Bounded:
# when full, an arbitrary half is evicted instead of a wholesale clear,
# so recent stamps are never re-admitted en masse.  Cross-PROCESS
# handovers can still record a second watch_lag for one stamp — bounded
# by WATCH_LAG_MAX_S and documented in docs/observability.md.
_lag_seen: set = set()
_lag_lock = threading.Lock()


def first_lag_observation(trace_id: str, span_id: str) -> bool:
    """True exactly once per stamp per process — the watch_lag
    recording gate (Controller._note_event)."""
    key = (trace_id, span_id)
    with _lag_lock:
        if key in _lag_seen:
            return False
        if len(_lag_seen) > 8192:
            for _ in range(4096):
                _lag_seen.pop()
        _lag_seen.add(key)
        return True


def record(name: str, *, trace_id: str, **kwargs) -> Optional[dict]:
    """Record one causal span into the process store (no-op when
    JOURNEY_DISABLE is set).  Marks the recording thread (see
    consume_mark) so the controller can tell an acting reconcile from a
    steady-state no-op sweep."""
    if not ENABLED:
        return None
    _local.mark = True
    return STORE.record(name, trace_id=trace_id, **kwargs)


def mark_thread() -> None:
    """Set the acting mark on the CURRENT thread — the FlightPool uses
    this to propagate marks recorded inside fanned-out slots (pool
    threads have their own thread-locals) back to the submitting
    reconcile worker."""
    _local.mark = True


def consume_mark() -> bool:
    """True when this thread recorded any span since the last call —
    the controller's acting-reconcile test: a resync sweep reconciles
    every key as a no-op, and retaining a span per no-op would grow the
    journey store (and the resync allocation band) with segments that
    say nothing."""
    marked = getattr(_local, "mark", False)
    _local.mark = False
    return marked


def journey(trace_id: str) -> List[dict]:
    return STORE.journey(trace_id)


def record_write(verb: str, kind: str, name: str, start_ts: float, *,
                 ok: bool = True, **attrs) -> None:
    """A child-write RTT span against the current context (the apply.*
    helpers' hook) — segment ``write_rtt``, parented on the reconcile's
    span so the journey shows which reconcile paid which write."""
    ctx = current()
    if ctx is None:
        return
    record(f"k8s.{verb}", trace_id=ctx.trace_id,
           parent_span_id=ctx.span_id, segment="write_rtt",
           start_ts=start_ts, end_ts=time.time(), kind=kind, object=name,
           ok=ok, **attrs)


def merge_journeys(*span_lists: List[dict]) -> List[dict]:
    """Join per-replica journey exports (the /debug/journey bodies of a
    ShardedFleet, or conformance's per-store reads) into one timeline:
    dedupe by span_id (a span is recorded by exactly one replica; the
    same export read twice must not double segments), sort by time."""
    seen = set()
    merged: List[dict] = []
    for spans in span_lists:
        for s in spans or []:
            key = s.get("span_id")
            if key in seen:
                continue
            seen.add(key)
            merged.append(s)
    merged.sort(key=lambda s: (s.get("start_ts", 0.0),
                               s.get("end_ts", 0.0)))
    return merged
