"""A bounded in-process time-series store — the storage layer of the
fleet metrics pipeline (docs/observability.md "The metrics pipeline").

Everything upstream of this module *emits* telemetry point-in-time
(Prometheus registries, scraped /metrics pages); everything downstream
*decides* from history (burn-rate SLO alerts, the InferenceService
autoscaler's TTFT deltas, goodput integration).  The TSDB is the seam:
append-only samples into fixed-capacity ring buffers per
``(name, labels)`` series, plus a small PURE query surface —

* ``instant``/``values_at``/``window`` — point and range lookups;
* ``increase``/``rate`` — counter math, **reset-aware** (a replica
  restart drops a counter to ~0; the pre-reset head must neither be
  lost nor read as a negative rate);
* ``histogram_quantile`` — Prometheus-style quantile estimation over
  stored ``*_bucket`` series (grouped by labels sans ``le``, merged,
  interpolated through the shared ``quantile_from_buckets``), either at
  an instant or over a windowed increase;
* ``merged_at`` — the exact-timestamp bucket merge the InferenceService
  autoscaler's pass-delta path is built on.

Bounds (both knobless constructor parameters — the OWNING layer sizes
them, see fleetscrape): ``capacity`` samples per series (ring — old
samples fall off), ``max_series`` series total (exceeding it evicts the
series with the OLDEST last sample first: a target that stopped
reporting is the stale one, not the hot series that just appended).
Thread-safe; no platform imports — the telemetry core stays dependency-
free so both planes (and tests) can hold one without a control plane.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from kubeflow_tpu.telemetry.metrics import quantile_from_buckets

DEFAULT_CAPACITY = 360          # ~1.5h at a 15 s cadence
DEFAULT_MAX_SERIES = 8192

LabelItems = Tuple[Tuple[str, str], ...]


def _labelkey(labels: Optional[Dict[str, str]]) -> LabelItems:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _matches(labelkey: LabelItems, matcher: Optional[Dict[str, str]]) -> bool:
    if not matcher:
        return True
    have = dict(labelkey)
    return all(have.get(k) == str(v) for k, v in matcher.items())


class _Series:
    __slots__ = ("name", "labelkey", "samples", "last_ts")

    def __init__(self, name: str, labelkey: LabelItems, capacity: int):
        self.name = name
        self.labelkey = labelkey
        self.samples: deque = deque(maxlen=capacity)  # (ts, value)
        self.last_ts = -math.inf


class TSDB:
    """The store.  All public methods are thread-safe."""

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self.evictions = 0           # series evicted at the max_series bound
        self.appends = 0             # samples ever appended (bench counter)
        self._lock = threading.Lock()
        # (name, labelkey) -> _Series, plus a name index so every query
        # touches only same-name series — rule evaluation must stay
        # O(matching series), never O(store) (the bench band's tripwire).
        self._series: Dict[Tuple[str, LabelItems], _Series] = {}
        self._by_name: Dict[str, Dict[LabelItems, _Series]] = {}

    # -- writes ---------------------------------------------------------------

    def append(self, name: str, labels: Optional[Dict[str, str]] = None,
               value: float = 0.0, ts: Optional[float] = None) -> None:
        """Append one sample.  ``ts`` defaults to nothing deliberately —
        the scrape layer stamps ONE timestamp per pass so a pass's
        samples are exact-ts joinable (``values_at``/``merged_at``)."""
        if ts is None:
            import time

            ts = time.time()
        key = (name, _labelkey(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self._evict_locked()
                series = _Series(name, key[1], self.capacity)
                self._series[key] = series
                self._by_name.setdefault(name, {})[key[1]] = series
            series.samples.append((float(ts), float(value)))
            if ts > series.last_ts:
                series.last_ts = float(ts)
            self.appends += 1

    def _evict_locked(self) -> None:
        """Evict the series whose LAST sample is oldest — the stale
        series a dead target left behind, never the one still appending
        (pinned by test_tsdb.py::test_stale_series_evicted_at_capacity)."""
        victim = min(self._series, key=lambda k: self._series[k].last_ts)
        self._del_locked(victim)
        self.evictions += 1

    def _del_locked(self, key: Tuple[str, LabelItems]) -> None:
        del self._series[key]
        bucket = self._by_name.get(key[0])
        if bucket is not None:
            bucket.pop(key[1], None)
            if not bucket:
                del self._by_name[key[0]]

    def drop(self, name: Optional[str] = None,
             matcher: Optional[Dict[str, str]] = None) -> int:
        """Delete matching series (a deleted service's scrape memory);
        returns the count dropped."""
        with self._lock:
            gone = [k for k, s in self._series.items()
                    if (name is None or s.name == name)
                    and _matches(s.labelkey, matcher)]
            for k in gone:
                self._del_locked(k)
            return len(gone)

    # -- introspection --------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def names(self) -> List[str]:
        with self._lock:
            return sorted({s.name for s in self._series.values()})

    def labelsets(self, name: str,
                  matcher: Optional[Dict[str, str]] = None
                  ) -> List[Dict[str, str]]:
        return [dict(lk) for lk, _ in self._select(name, matcher)]

    def _select(self, name: str, matcher: Optional[Dict[str, str]]
                ) -> List[Tuple[LabelItems, List[Tuple[float, float]]]]:
        with self._lock:
            return [(lk, list(s.samples))
                    for lk, s in self._by_name.get(name, {}).items()
                    if _matches(lk, matcher)]

    # -- reads ----------------------------------------------------------------

    def instant(self, name: str, matcher: Optional[Dict[str, str]] = None,
                at: Optional[float] = None,
                staleness: Optional[float] = None
                ) -> List[Tuple[Dict[str, str], float, float]]:
        """Latest sample at or before ``at`` per matching series, as
        ``(labels, ts, value)``.  ``staleness`` drops series whose latest
        sample is older than ``at - staleness`` (a dead scrape target's
        frozen last value must not read as live — the goodput
        no-double-count contract)."""
        out = []
        for lk, samples in self._select(name, matcher):
            picked = None
            for ts, v in reversed(samples):
                if at is None or ts <= at:
                    picked = (ts, v)
                    break
            if picked is None:
                continue
            if (staleness is not None and at is not None
                    and picked[0] < at - staleness):
                continue
            out.append((dict(lk), picked[0], picked[1]))
        return out

    def values_at(self, name: str, matcher: Optional[Dict[str, str]] = None,
                  ts: float = 0.0, eps: float = 1e-9
                  ) -> List[Tuple[Dict[str, str], float]]:
        """Samples at EXACTLY ``ts`` (± eps) — the scrape-pass join: one
        pass stamps one timestamp, so a series that missed the pass is
        absent rather than contributing its stale last value."""
        out = []
        for lk, samples in self._select(name, matcher):
            for sts, v in reversed(samples):
                if abs(sts - ts) <= eps:
                    out.append((dict(lk), v))
                    break
                if sts < ts - eps:
                    break
        return out

    def window(self, name: str, matcher: Optional[Dict[str, str]] = None,
               start: float = -math.inf, end: float = math.inf
               ) -> List[Tuple[Dict[str, str], List[Tuple[float, float]]]]:
        """Range lookup: every matching series' samples in [start, end]."""
        return [(dict(lk), [(ts, v) for ts, v in samples
                            if start <= ts <= end])
                for lk, samples in self._select(name, matcher)]

    def latest_n(self, name: str, matcher: Optional[Dict[str, str]] = None,
                 n: int = 2) -> List[Tuple[float, float]]:
        """Newest ``n`` samples (ts, value) across matching series,
        newest first — the autoscaler reads its scrape-pass records
        (this pass + the previous) through this."""
        merged: List[Tuple[float, float]] = []
        for _lk, samples in self._select(name, matcher):
            merged.extend(samples)
        merged.sort(key=lambda s: s[0], reverse=True)
        return merged[:n]

    # -- counter math ---------------------------------------------------------

    @staticmethod
    def _increase_of(samples: List[Tuple[float, float]]) -> float:
        """Reset-aware increase across consecutive samples: a drop means
        the counter restarted (replica restart) — the post-reset value IS
        the increase since the reset, and the pre-reset head is already
        accumulated.  Matches Prometheus ``increase`` up to its
        extrapolation (deliberately none here: scrape cadences are
        coarse and decisions prefer under- to over-counting)."""
        inc = 0.0
        prev = None
        for _ts, v in samples:
            if prev is not None:
                inc += v if v < prev else v - prev
            prev = v
        return inc

    @classmethod
    def _series_increase(cls, samples: List[Tuple[float, float]],
                         start: float, at: float) -> float:
        """One series' reset-aware increase over [start, at].  A series'
        first sample inside the window anchors against the last sample
        BEFORE the window when one exists (a window never misses the
        increase that landed exactly on its edge).  A series with no
        prior sample contributes only deltas BETWEEN its in-window
        samples — Prometheus semantics: a single cumulative observation
        is history, not an increase.  (Counting a first-ever sample at
        its full value would read a long-lived remote counter's whole
        lifetime as in-window events on the first scrape after a
        restart — a spurious burn-rate page on a healthy fleet.)"""
        inside = [(ts, v) for ts, v in samples if start <= ts <= at]
        if not inside:
            return 0.0
        before = [(ts, v) for ts, v in samples if ts < start]
        if before:
            inside = [before[-1]] + inside
        return cls._increase_of(inside)

    def increase(self, name: str, matcher: Optional[Dict[str, str]] = None,
                 window: float = math.inf, at: Optional[float] = None
                 ) -> float:
        """Summed reset-aware increase over the window ending at ``at``
        for every matching counter series (see ``_series_increase`` for
        the edge semantics)."""
        if at is None:
            import time

            at = time.time()
        start = at - window
        return sum(self._series_increase(samples, start, at)
                   for _lk, samples in self._select(name, matcher))

    def rate(self, name: str, matcher: Optional[Dict[str, str]] = None,
             window: float = 300.0, at: Optional[float] = None) -> float:
        """increase / window — per-second counter rate."""
        if window <= 0:
            return 0.0
        return self.increase(name, matcher, window=window, at=at) / window

    # -- histograms -----------------------------------------------------------

    def merged_at(self, bucket_name: str,
                  matcher: Optional[Dict[str, str]] = None,
                  ts: Optional[float] = None, *, exact: bool = True
                  ) -> Dict[float, float]:
        """Cumulative buckets ``{le: value}`` merged (summed) over every
        matching series at one timestamp.  ``exact=True`` joins on the
        scrape-pass timestamp (``values_at`` semantics: a series absent
        from that pass contributes nothing); ``exact=False`` takes each
        series' latest sample at or before ``ts``."""
        buckets: Dict[float, float] = {}
        if exact and ts is not None:
            rows = [(labels, v)
                    for labels, v in self.values_at(bucket_name, matcher, ts)]
        else:
            rows = [(labels, v)
                    for labels, _sts, v in self.instant(bucket_name, matcher,
                                                        at=ts)]
        for labels, v in rows:
            le = labels.get("le")
            if le is None:
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            buckets[bound] = buckets.get(bound, 0.0) + v
        return buckets

    def bucket_increases(self, bucket_name: str,
                         matcher: Optional[Dict[str, str]] = None,
                         window: float = math.inf,
                         at: Optional[float] = None) -> Dict[float, float]:
        """Windowed reset-aware increase per ``le`` bound, merged over
        matching series — the burn-rate engine's good/total source.  ONE
        pass over the matching series (each series carries exactly one
        ``le``), never a rescan per bound."""
        if at is None:
            import time

            at = time.time()
        start = at - window
        out: Dict[float, float] = {}
        for lk, samples in self._select(bucket_name, matcher):
            le = dict(lk).get("le")
            if le is None:
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            out[bound] = (out.get(bound, 0.0)
                          + self._series_increase(samples, start, at))
        return out

    def histogram_quantile(self, q: float, bucket_name: str,
                           matcher: Optional[Dict[str, str]] = None, *,
                           window: Optional[float] = None,
                           at: Optional[float] = None) -> Optional[float]:
        """Prometheus-style quantile over stored bucket series: with
        ``window``, over the reset-aware windowed increase (what a
        recording rule wants); without, over the cumulative merge at
        ``at`` (whole-history quantile).  None on empty/sparse-empty
        buckets, same as ``quantile_from_buckets``."""
        if window is not None:
            buckets = self.bucket_increases(bucket_name, matcher,
                                            window=window, at=at)
        else:
            buckets = self.merged_at(bucket_name, matcher, ts=at, exact=False)
        # Sparse series can yield empty or all-zero merges; the shared
        # interpolator returns None for both.
        return quantile_from_buckets(buckets, q)

    # -- text ingestion -------------------------------------------------------

    def ingest_page(self, text: str,
                    labels: Optional[Dict[str, str]] = None,
                    ts: Optional[float] = None,
                    names: Optional[Iterable[str]] = None) -> int:
        """Parse one Prometheus exposition page and append every sample
        (bucket/sum/count expansions included) with ``labels`` merged
        over the sample's own.  Returns the sample count; raises
        ``ValueError`` on an unparseable page (the scrape layer counts
        it as reason="parse")."""
        from prometheus_client.parser import text_string_to_metric_families

        if ts is None:
            import time

            ts = time.time()
        wanted = set(names) if names is not None else None
        n = 0
        # The parser raises on malformed lines lazily; materialize inside
        # the try so a torn page is one clean ValueError for the caller.
        try:
            families = [(fam.name, [(s.name, dict(s.labels), s.value)
                                    for s in fam.samples])
                        for fam in text_string_to_metric_families(text)]
        except Exception as e:
            raise ValueError(f"unparseable metrics page: {e}") from e
        for _fam, samples in families:
            for sname, slabels, value in samples:
                if wanted is not None and sname not in wanted:
                    continue
                merged = dict(slabels)
                if labels:
                    merged.update(labels)
                self.append(sname, merged, value, ts=ts)
                n += 1
        return n
