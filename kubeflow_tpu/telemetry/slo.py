"""Declarative SLO rules over the fleet TSDB: multi-window burn-rate
alerting + recording rules (docs/observability.md "The metrics
pipeline").

The model is the SRE-workbook burn-rate pattern on latency SLOs: an
objective says "``objective`` of events complete within ``threshold``
seconds"; from a histogram's buckets, *good* = the windowed increase of
the largest bucket at or under the threshold and *total* = the
``+Inf`` bucket's increase, so

    error_ratio = 1 - good / total
    burn_rate   = error_ratio / (1 - objective)

A burn rate of 1.0 spends the error budget exactly over the SLO period;
the alert FIRES only when BOTH a fast window (5m-style — catches a
cliff within one evaluation cadence) and a slow window (1h-style —
keeps a single bad scrape from paging) burn above their thresholds, and
RESOLVES when either recovers.  Window lengths, burn thresholds, the
objective and per-SLO latency thresholds all scale through
``config.knob`` (the R005 registry — /debug/knobs shows the live
surface).

Alert state transitions are counted in ``kft_alert_transitions_total``
and mirrored into ``kft_alerts_firing``; with a client attached, each
transition is recorded as ONE fleet-wide Kubernetes Event through the
stamping apply helpers: the Event name and owned content are
deterministic functions of the alert, so ``create_or_update``'s
content-hash makes N replicas evaluating the same rules emit exactly
one object (the second replica's apply is a no-op; a create race
resolves through AlreadyExists).  ``/debug/alerts`` serves the live
state via the same single-slot registry pattern as /debug/queue.
"""
from __future__ import annotations

import dataclasses
import logging
import math
import time
from typing import Dict, List, Optional, Tuple

from kubeflow_tpu.platform import config
from kubeflow_tpu.telemetry.tsdb import TSDB

log = logging.getLogger("kubeflow_tpu.telemetry.slo")

STATE_INACTIVE = "inactive"
STATE_FIRING = "firing"


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """One latency-SLO burn-rate alert over a stored bucket series."""

    name: str                      # alert name (bounded label value)
    metric: str                    # bucket series, e.g. "..._seconds_bucket"
    threshold: float               # latency objective bound (seconds)
    objective: float = 0.99        # fraction of events under threshold
    matcher: Tuple[Tuple[str, str], ...] = ()
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.4        # SRE-workbook page thresholds
    slow_burn: float = 6.0
    min_events: float = 1.0        # fast-window total below this = no signal
    doc: str = ""

    def burn_rates(self, tsdb: TSDB, at: float
                   ) -> Tuple[Optional[float], Optional[float], float]:
        """(fast_burn_rate, slow_burn_rate, fast_total_events); None
        where the window holds no events (no signal ≠ healthy ≠ burning
        — an absent series must neither fire nor resolve-with-proof)."""
        m = dict(self.matcher)
        fast = self._burn(tsdb, at, self.fast_window_s, m)
        slow = self._burn(tsdb, at, self.slow_window_s, m)
        return fast[0], slow[0], fast[1]

    def _burn(self, tsdb: TSDB, at: float, window: float, matcher: dict
              ) -> Tuple[Optional[float], float]:
        buckets = tsdb.bucket_increases(self.metric, matcher,
                                        window=window, at=at)
        total = buckets.get(math.inf, 0.0)
        if total <= 0:
            return None, 0.0
        # Good = the largest bucket bound at or under the threshold
        # (cumulative buckets: that IS the count within objective); a
        # threshold between bounds degrades conservatively to the bound
        # below it.
        good_bounds = [b for b in buckets
                       if b != math.inf and b <= self.threshold + 1e-12]
        good = buckets[max(good_bounds)] if good_bounds else 0.0
        error_ratio = min(max(1.0 - good / total, 0.0), 1.0)
        budget = max(1.0 - self.objective, 1e-9)
        return error_ratio / budget, total


@dataclasses.dataclass(frozen=True)
class RecordingRule:
    """Precompute a quantile over a bucket series into a new stored
    series (``record``) each evaluation — dashboards and later rules
    read the recorded series instead of re-walking buckets."""

    record: str                    # output series name
    metric: str                    # input bucket series
    q: float = 0.99
    window_s: float = 300.0
    matcher: Tuple[Tuple[str, str], ...] = ()

    def evaluate(self, tsdb: TSDB, at: float) -> Optional[float]:
        value = tsdb.histogram_quantile(self.q, self.metric,
                                        dict(self.matcher),
                                        window=self.window_s, at=at)
        if value is not None:
            tsdb.append(self.record, dict(self.matcher), value, ts=at)
        return value


@dataclasses.dataclass
class AlertState:
    state: str = STATE_INACTIVE
    since: float = 0.0
    fast_burn: Optional[float] = None
    slow_burn: Optional[float] = None
    transitions: int = 0


def default_rules() -> List[BurnRateRule]:
    """The four fleet SLOs (docs/observability.md lists the knob table):
    serve TTFT p99, reconcile p99, informer watch-lag, TPUJob queue
    wait.  Thresholds default to existing histogram bucket bounds so the
    good-bucket lookup is exact."""
    fast = config.knob("KFT_SLO_FAST_WINDOW_SECONDS", 300.0, float,
                       doc="burn-rate fast window (the paging window)")
    slow = config.knob("KFT_SLO_SLOW_WINDOW_SECONDS", 3600.0, float,
                       doc="burn-rate slow window (the confirmation window)")
    fast_burn = config.knob("KFT_SLO_FAST_BURN", 14.4, float,
                            doc="fast-window burn-rate page threshold")
    slow_burn = config.knob("KFT_SLO_SLOW_BURN", 6.0, float,
                            doc="slow-window burn-rate page threshold")
    objective = config.knob("KFT_SLO_OBJECTIVE", 0.99, float,
                            doc="fraction of events that must land under "
                                "each SLO's latency threshold")

    def rule(name, metric, threshold_knob, threshold_default, doc):
        return BurnRateRule(
            name=name, metric=metric,
            threshold=config.knob(threshold_knob, threshold_default, float,
                                  doc=f"{name} latency threshold (s)"),
            objective=objective, fast_window_s=fast, slow_window_s=slow,
            fast_burn=fast_burn, slow_burn=slow_burn, doc=doc)

    return [
        rule("serve-ttft-p99",
             "serve_time_to_first_token_seconds_bucket",
             "KFT_SLO_TTFT_SECONDS", 5.0,
             "time-to-first-token across scraped serving replicas"),
        rule("reconcile-p99",
             "controller_runtime_reconcile_time_seconds_bucket",
             "KFT_SLO_RECONCILE_SECONDS", 1.0,
             "control-plane reconcile latency (self-scrape)"),
        rule("watch-lag",
             "informer_watch_lag_seconds_bucket",
             "KFT_SLO_WATCH_LAG_SECONDS", 5.0,
             "API write -> watch delivery lag (self-scrape)"),
        rule("queue-wait",
             "tpujob_queue_wait_seconds_bucket",
             "KFT_SLO_QUEUE_WAIT_SECONDS", 300.0,
             "TPUJob admission-queue wait (self-scrape)"),
    ]


class RuleEngine:
    """Evaluate burn-rate + recording rules on a cadence; own the alert
    state machine and its fleet-wide Event emission."""

    def __init__(self, tsdb: TSDB, rules: Optional[List[BurnRateRule]] = None,
                 *, recording: Optional[List[RecordingRule]] = None,
                 client=None, namespace: str = "kubeflow",
                 component: str = "slo-engine", incidents=None, now=time.time):
        self.tsdb = tsdb
        self.rules = list(default_rules() if rules is None else rules)
        self.recording = list(recording or [])
        self.client = client
        self.namespace = namespace
        self.component = component
        # Optional flight recorder (telemetry/incidents.py): every
        # transition TO firing captures one evidence bundle.  Kept as a
        # plain attribute so tests and MetricsPipeline can attach one
        # after construction.
        self.incidents = incidents
        self.now = now
        self.states: Dict[str, AlertState] = {
            r.name: AlertState() for r in self.rules}
        self.last_eval_at: Optional[float] = None

    # -- evaluation -----------------------------------------------------------

    def evaluate(self, at: Optional[float] = None) -> List[dict]:
        """One evaluation pass; returns the transitions it caused
        (``[{"alert", "state", "fast_burn", "slow_burn"}]``)."""
        from kubeflow_tpu.platform.runtime import metrics

        if at is None:
            at = self.now()
        self.last_eval_at = at
        for rec in self.recording:
            rec.evaluate(self.tsdb, at)
        transitions: List[dict] = []
        for rule in self.rules:
            fast, slow, events = rule.burn_rates(self.tsdb, at)
            st = self.states[rule.name]
            st.fast_burn, st.slow_burn = fast, slow
            burning = (fast is not None and slow is not None
                       and events >= rule.min_events
                       and fast > rule.fast_burn and slow > rule.slow_burn)
            if burning and st.state != STATE_FIRING:
                self._transition(rule, st, STATE_FIRING, at, transitions)
            elif (not burning and st.state == STATE_FIRING
                  and fast is not None):
                # Recovery needs evidence (a window with events that no
                # longer burns), not silence: a target outage mid-page
                # must not auto-resolve the page.
                self._transition(rule, st, STATE_INACTIVE, at, transitions)
            metrics.kft_alerts_firing.labels(alert=rule.name).set(
                1.0 if st.state == STATE_FIRING else 0.0)
        return transitions

    def _transition(self, rule: BurnRateRule, st: AlertState,
                    to_state: str, at: float,
                    transitions: List[dict]) -> None:
        from kubeflow_tpu.platform.runtime import metrics

        st.state = to_state
        st.since = at
        st.transitions += 1
        label = "firing" if to_state == STATE_FIRING else "resolved"
        metrics.kft_alert_transitions_total.labels(
            alert=rule.name, state=label).inc()
        transitions.append({"alert": rule.name, "state": label,
                            "fast_burn": st.fast_burn,
                            "slow_burn": st.slow_burn})
        self._emit_event(rule, firing=(to_state == STATE_FIRING))
        if to_state == STATE_FIRING and self.incidents is not None:
            # Page-time evidence: the flight recorder snapshots the burn
            # window, worst journeys, profile window and debug surfaces
            # into one bundle (debounced per alert inside capture()).
            # A capture failure must never break the alert state machine.
            try:
                self.incidents.capture(rule, st, at, engine=self)
            except Exception:
                log.debug("incident capture for %s failed", rule.name,
                          exc_info=True)

    def _emit_event(self, rule: BurnRateRule, *, firing: bool) -> None:
        """One fleet-wide Event per transition, through the stamping
        apply helpers.  Name AND owned content are deterministic in
        (alert, state) — every replica generates the same object, so the
        content hash makes the second apply a no-op and a create race
        lands on AlreadyExists: exactly one Event object fleet-wide,
        flipped in place on resolve (the ShardedFleet pin in
        test_slo.py)."""
        if self.client is None:
            return
        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import EVENT
        from kubeflow_tpu.platform.runtime.apply import create_or_update

        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": f"kft-alert-{rule.name}",
                         "namespace": self.namespace},
            "involvedObject": {"kind": "FleetSLO", "name": rule.name,
                               "namespace": self.namespace},
            "type": "Warning" if firing else "Normal",
            "reason": "AlertFiring" if firing else "AlertResolved",
            # Deterministic on purpose: burn-rate values differ per
            # replica/evaluation and would defeat the cross-replica
            # content-hash dedup; the live numbers are on /debug/alerts.
            "message": (f"burn-rate alert {rule.name} "
                        f"{'firing' if firing else 'resolved'}: "
                        f"{rule.doc or rule.metric} vs "
                        f"{rule.threshold:g}s objective "
                        f"{rule.objective:g}"),
            "source": {"component": self.component},
        }
        try:
            create_or_update(
                self.client, EVENT, ev,
                owned_fields=("type", "reason", "message",
                              "involvedObject", "source"))
        except errors.AlreadyExists:
            pass  # a sibling replica announced this transition first
        except errors.ApiError:
            log.debug("alert event emission failed", exc_info=True)

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/alerts payload."""
        alerts = []
        for rule in self.rules:
            st = self.states[rule.name]
            alerts.append({
                "alert": rule.name,
                "state": st.state,
                "since": round(st.since, 3) if st.since else None,
                "fastBurn": (round(st.fast_burn, 3)
                             if st.fast_burn is not None else None),
                "slowBurn": (round(st.slow_burn, 3)
                             if st.slow_burn is not None else None),
                "metric": rule.metric,
                "thresholdSeconds": rule.threshold,
                "objective": rule.objective,
                "windows": {"fastSeconds": rule.fast_window_s,
                            "slowSeconds": rule.slow_window_s,
                            "fastBurnThreshold": rule.fast_burn,
                            "slowBurnThreshold": rule.slow_burn},
                "transitions": st.transitions,
                "doc": rule.doc,
            })
        return {"alerts": alerts,
                "lastEvalAt": (round(self.last_eval_at, 3)
                               if self.last_eval_at else None)}


# -- /debug/alerts registry (single-slot, like jobqueue's) --------------------

_debug_engine: Optional[RuleEngine] = None


def register_debug_alerts(engine: Optional[RuleEngine]) -> None:
    global _debug_engine
    _debug_engine = engine


def debug_snapshot() -> Optional[dict]:
    e = _debug_engine
    return e.snapshot() if e is not None else None
