"""Plane-agnostic span tracing: ONE implementation for both halves.

PR 1 built per-reconcile tracing for the control plane
(platform/runtime/trace.py); the compute plane (train steps, serve
requests) needs the identical machinery — thread-carried traces, bounded
ring buffer, slow-trace JSON dumps.  This module is that machinery lifted
into a shared core: a ``Tracer`` owns its own thread-local slot, ring
buffer, and logger, so the control plane's reconcile traces, the train
loop's step traces, and a serve app's request traces never interleave,
while span/dump semantics stay byte-compatible everywhere.

Design points carried over verbatim from the PR-1 implementation:

* the active trace rides a thread-local — spans opened anywhere
  downstream attach without plumbing a context object through signatures;
* completed traces land in a bounded deque (the ``/debug/traces`` body);
* traces slower than a caller-supplied threshold dump their whole span
  tree as ONE structured JSON log line;
* trace ids are one urandom read per process (the prefix) plus a counter
  — never a syscall per trace (the bench_scale resync-CPU finding).

``platform/runtime/trace.py`` wraps a Tracer in the PR-1 module API (same
env knobs, same logger name); ``telemetry/compute.py`` and
``telemetry/serve.py`` instantiate their own.
"""
from __future__ import annotations

import collections
import json
import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from kubeflow_tpu.telemetry import causal, profiler


def filter_traces(traces: List[dict], *, n: Optional[int] = None,
                  trace_id: Optional[str] = None,
                  **fields: Optional[str]) -> List[dict]:
    """THE /debug/traces query contract, shared by the controllers'
    endpoint (platform/main.py) and the serve apps' (models/serve.py) so
    it cannot drift (docs/observability.md "The /debug/traces
    contract"): ``trace_id`` matches a trace's own id OR its
    ``causal_trace_id`` journey link; extra ``fields`` (e.g.
    ``controller=``) match exactly; filters apply BEFORE the ``n`` cap,
    which keeps the newest n matches (n <= 0 returns nothing)."""
    if trace_id:
        traces = [t for t in traces
                  if t.get("trace_id") == trace_id
                  or t.get("causal_trace_id") == trace_id]
    for key, want in fields.items():
        if want:
            traces = [t for t in traces if t.get(key) == want]
    if n is not None:
        traces = traces[-n:] if n > 0 else []
    return traces


class Span:
    __slots__ = ("name", "offset_s", "duration_s", "attrs")

    def __init__(self, name: str, offset_s: float, attrs: Dict):
        self.name = name
        self.offset_s = offset_s
        self.duration_s = 0.0
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "offset_ms": round(self.offset_s * 1e3, 3),
            "duration_ms": round(self.duration_s * 1e3, 3),
        }
        if self.attrs:
            d.update(self.attrs)
        return d


class Trace:
    """One traced unit of work (a reconcile, a train step, a serve
    request).  ``keys`` names the two identity fields in the exported
    dict — ("controller", "request") on the control plane,
    ("component", "request") elsewhere — so each plane's wire format
    reads naturally while the machinery stays shared."""

    def __init__(self, component: str, name: str,
                 keys: Tuple[str, str] = ("component", "request")):
        # 128-bit ids from the causal counter-in-random-block mint (one
        # secrets read per PROCESS, never a syscall per trace): the PR-1
        # 16-hex prefix+counter ids could collide across sharded
        # replicas in a merged journey (pinned in test_sharding.py).
        self.trace_id = causal.new_trace_id()
        self.component = component
        self.name = name
        self.keys = keys
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        self.spans: List[Span] = []
        self.result = ""
        # Cross-trace links merged flat into to_dict() — the reconcile
        # path sets causal_trace_id/causal_span_id here so
        # /debug/traces?trace_id= finds every reconcile of a journey.
        self.links: Dict[str, str] = {}

    def add_span(self, name: str, *, duration_s: float, offset_s: float = 0.0,
                 **attrs) -> Span:
        """Record an already-measured span (e.g. a queue wait that elapsed
        before the trace began)."""
        sp = Span(name, offset_s, attrs)
        sp.duration_s = duration_s
        self.spans.append(sp)
        return sp

    def to_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            self.keys[0]: self.component,
            self.keys[1]: self.name,
            "start_ts": round(self.start_ts, 3),
            "duration_ms": round(
                (time.perf_counter() - self._t0) * 1e3, 3),
            "result": self.result,
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.links:
            d.update(self.links)
        return d


class Tracer:
    """A plane's trace domain: its own thread-local active slot, ring
    buffer, and slow-dump logger.  All methods mirror the PR-1 module
    functions one-to-one."""

    def __init__(self, name: str, *,
                 keys: Tuple[str, str] = ("component", "request"),
                 buffer_size: int = 64,
                 logger: str = "kubeflow_tpu.telemetry.trace",
                 slow_message: str = "slow trace"):
        self.name = name
        self.keys = keys
        self.slow_message = slow_message
        self.log = logging.getLogger(logger)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._recent: collections.deque = collections.deque(
            maxlen=buffer_size)

    def begin(self, component: str, name: str, *,
              enabled: bool = True) -> Optional[Trace]:
        """Start a trace on the current thread (None when disabled).  Any
        stale trace (prior work that died without finish()) is discarded —
        traces never leak across units of work."""
        if not enabled:
            self._local.trace = None
            profiler.clear_active_role()
            return None
        tr = Trace(component, name, self.keys)
        self._local.trace = tr
        # The profiler's attribution seam: while this trace is active,
        # samples of this thread fold under the traced component (the
        # reconciling controller, the serving model, the train step).
        profiler.set_active_role(component)
        return tr

    def current(self) -> Optional[Trace]:
        return getattr(self._local, "trace", None)

    def adopt(self, tr: Optional[Trace]) -> None:
        """Install an EXISTING trace as this thread's active one — the
        FlightPool carry: a span opened inside a fanned-out flight slot
        must land in the submitting reconcile's trace, not the worker
        thread's (list.append on the shared span list is atomic under
        the GIL)."""
        self._local.trace = tr
        # Carry profile attribution with the trace: a slot sampled
        # mid-flight folds under the SUBMITTING component's role, not
        # the pool's; adopt(None) at slot exit restores the pool role.
        profiler.set_active_role(tr.component if tr is not None else None)

    def active(self) -> bool:
        return getattr(self._local, "trace", None) is not None

    @contextmanager
    def span(self, name: str, **attrs):
        """Open a child span on the current thread's trace; no-op (yields
        None) when no trace is active, so library code can instrument
        unconditionally."""
        tr = getattr(self._local, "trace", None)
        if tr is None:
            yield None
            return
        t0 = time.perf_counter()
        sp = Span(name, t0 - tr._t0, attrs)
        try:
            yield sp
        finally:
            sp.duration_s = time.perf_counter() - t0
            tr.spans.append(sp)

    def finish(self, result: str = "", *,
               slow_seconds: Optional[float] = None) -> Optional[dict]:
        """Close the current thread's trace: record it in the ring buffer
        and, when it crossed ``slow_seconds``, dump the span tree as one
        JSON log line.  Returns the trace dict (None when no trace was
        active)."""
        tr = getattr(self._local, "trace", None)
        if tr is None:
            return None
        self._local.trace = None
        profiler.clear_active_role()
        tr.result = result
        d = tr.to_dict()
        slow = (slow_seconds is not None
                and d["duration_ms"] >= slow_seconds * 1e3)
        if slow:
            # Point the dump at the covering profile window: the "why"
            # for this slow trace is the flamegraph that was already
            # being collected while it ran (/debug/profile?window=N).
            wid = profiler.covering_window_id()
            if wid is not None:
                d["profile_window"] = wid
        with self._lock:
            self._recent.append(d)
        if slow:
            self.log.warning(
                "%s: %s", self.slow_message, json.dumps(d, sort_keys=True))
        return d

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """Most recent completed traces, newest last (the /debug/traces
        body).  ``n`` caps the result; n <= 0 returns nothing (``out[-0:]``
        would be everything)."""
        with self._lock:
            out = list(self._recent)
        if n is None:
            return out
        return out[-n:] if n > 0 else []

    def clear(self) -> None:
        """Test helper: empty the ring buffer."""
        with self._lock:
            self._recent.clear()
