"""Compute-plane telemetry: step timing, throughput/MFU accounting, and
HBM watermarks — the train/serve counterpart of the control plane's
runtime/metrics.py.

Three jobs, one registry:

* **Step telemetry.** ``train_step_seconds{phase=compile|run}`` is fed by
  the train loop (one observation per optimizer step, host wall time) and
  by bench.py's measurement windows; scrape-time p50/p99 gauges ride on
  top.  Throughput gauges (``train_tokens_per_sec`` /
  ``train_model_tflops_per_sec`` / ``train_mfu``) are set through
  ``update_throughput`` — the SAME accounting bench.py prints
  (tokens/s × model FLOPs/token ÷ chip peak; see BASELINE.md "MFU
  accounting"), so a live gauge and a BENCH json can never disagree.
* **HBM watermarks.** ``device_memory_bytes{device,kind}`` samples
  ``jax.Device.memory_stats()`` at scrape time; backends that return
  None (CPU) simply export no samples — absent gauges, never a crash.
  ``free_hbm_bytes``/``hbm_peak_bytes`` are the programmatic reads the
  attention pre-flight estimator and bench.py use.
* **Allocation pre-flight.** ``note_attention_estimate`` publishes an
  O(S²) attention footprint computed from shapes BEFORE any buffer is
  materialized and emits one structured warning line when the estimate
  crosses ``ATTENTION_HBM_BUDGET_FRACTION`` of free HBM — the BENCH_r05
  RESOURCE_EXHAUSTED (ROADMAP item 3) as a watched signal instead of a
  post-mortem.

Everything lives in the module-local ``registry`` (telemetry/metrics.py
hygiene contract); jax is imported lazily inside the samplers so
importing this module never initializes a backend.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Dict, Optional

from prometheus_client import Counter, Gauge, Histogram

from kubeflow_tpu.platform import config
from kubeflow_tpu.telemetry import metrics as tmetrics
from kubeflow_tpu.telemetry.trace import Tracer

log = logging.getLogger("kubeflow_tpu.telemetry.compute")

registry = tmetrics.new_registry()

# TPU v5e public spec: 197 bf16 TFLOP/s per chip (394 int8).  The MFU
# denominator for every accounting consumer (bench.py imports it from
# here); overridable per call for other parts.
V5E_BF16_PEAK_TFS = 197.0

# Steps at or above this wall time dump their span tree as one JSON log
# line (the step-level analog of TRACE_SLOW_RECONCILE_SECONDS).
# Env-tunable; tests set the module attribute directly.
TRAIN_SLOW_STEP_SECONDS = config.env_float("TRAIN_SLOW_STEP_SECONDS", 10.0)
# Step tracing on by default (control-plane convention): span overhead is
# microseconds against millisecond-to-second train steps.
STEP_TRACE_ENABLED = not config.env_bool("TRAIN_TRACE_DISABLE", False)
# Warn when a single attention call's O(S²) footprint estimate exceeds
# this fraction of currently-free HBM.
ATTENTION_HBM_BUDGET_FRACTION = config.env_float(
    "ATTENTION_HBM_BUDGET_FRACTION", 0.5)

# Per-step traces (data → dispatch → bookkeeping spans) from the train
# loop; slow steps dump through this tracer's logger.
train_tracer = Tracer(
    "train", keys=("component", "step"),
    buffer_size=config.env_int("TRAIN_TRACE_BUFFER_SIZE", 64),
    logger="kubeflow_tpu.train.trace",
    slow_message="slow train step trace",
)

_STEP_BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0, 60.0, 180.0, 600.0)

train_step_seconds = Histogram(
    "train_step_seconds",
    "Optimizer-step wall time by phase (compile = the first step of a "
    "loop/window, which pays jit compilation; run = steady state)",
    ["phase"], buckets=_STEP_BUCKETS, registry=registry,
)
train_steps_total = Counter(
    "train_steps_total", "Optimizer steps executed", registry=registry,
)
train_slow_steps_total = Counter(
    "train_slow_steps_total",
    "Steps that crossed TRAIN_SLOW_STEP_SECONDS (their span tree was "
    "dumped; see the kubeflow_tpu.train.trace logger)",
    registry=registry,
)
train_tokens_per_sec = Gauge(
    "train_tokens_per_sec",
    "Training throughput over the last completed log window",
    registry=registry,
)
train_model_tflops_per_sec = Gauge(
    "train_model_tflops_per_sec",
    "Useful model TFLOP/s over the last log window (tokens/s x model "
    "FLOPs/token; remat recompute not counted — the MFU convention)",
    registry=registry,
)
train_mfu = Gauge(
    "train_mfu",
    "Model FLOPs utilization over the last log window, against the "
    "configured chip peak (default: v5e bf16, 197 TF/s)",
    registry=registry,
)

attention_mask_bytes_estimate = Gauge(
    "attention_mask_bytes_estimate",
    "Pre-flight estimate of the O(S^2) bytes the XLA attention path will "
    "materialize (f32 logits + probs only — masking is iota-fused and "
    "allocation-free since ISSUE 7), computed from shapes BEFORE "
    "allocation — the BENCH_r05 RESOURCE_EXHAUSTED mode as a signal",
    registry=registry,
)
attention_kernel_calls_total = Counter(
    "attention_kernel_calls_total",
    "dot_product_attention calls by the implementation actually selected "
    "(trace-time count: one per attention site per jit trace) — the "
    "anti-silent-fallback signal ci/bench_smoke.py pins",
    ["impl"],
    registry=registry,
)
attention_mask_budget_warnings_total = Counter(
    "attention_mask_budget_warnings_total",
    "Attention calls whose footprint estimate exceeded "
    "ATTENTION_HBM_BUDGET_FRACTION of free HBM (one structured warning "
    "line each)",
    registry=registry,
)


# -- accounting (ONE formula for gauges, bench lines, and reports) ------------


def lm_train_flops_per_token(cfg, seq: int) -> float:
    """Model FLOPs per token for one LM train step (fwd + bwd = 3x fwd).

    Explicit accounting (written down in BASELINE.md "MFU accounting"):
    matmul FLOPs = 2*M*N*K; causal attention counts the score and value
    matmuls at HALF the full s^2 work (the flash kernel skips the upper
    triangle; XLA's masked arm does the full s^2, so its MFU reads
    conservatively low — stated in BASELINE.md).  Embedding lookup,
    norms, rotary and elementwise ops are omitted (<1% at these shapes).
    Remat recompute is NOT counted: MFU measures useful model FLOPs.

    Lives in the telemetry core (not bench.py, which re-exports it) so
    the train loop's live MFU gauge and the bench report lines share ONE
    accounting by construction.
    """
    d = cfg.dim
    kv_dim = d * cfg.n_kv_heads // cfg.n_heads
    proj = 2 * d * d + 2 * 2 * d * kv_dim + 2 * d * d  # q, k+v, o
    attn = 2 * 2 * seq * d / 2  # QK^T + AV at causal half-occupancy
    ffn = 3 * 2 * d * cfg.ffn_dim  # SwiGLU: gate, up, down
    head = 2 * d * cfg.vocab_size
    return 3.0 * (cfg.n_layers * (proj + attn + ffn) + head)


def model_tflops_per_sec(tokens_per_sec: float,
                         flops_per_token: float) -> float:
    return tokens_per_sec * flops_per_token / 1e12


def mfu(tokens_per_sec: float, flops_per_token: float,
        peak_tflops: float = V5E_BF16_PEAK_TFS) -> float:
    return model_tflops_per_sec(tokens_per_sec, flops_per_token) / peak_tflops


def update_throughput(tokens_per_sec: float, *,
                      flops_per_token: Optional[float] = None,
                      peak_tflops: Optional[float] = None) -> Dict[str, float]:
    """Refresh the throughput gauges from one completed window and return
    the derived values (the report-line fields).  FLOPs accounting is
    optional — without it only tokens/s is exported."""
    train_tokens_per_sec.set(tokens_per_sec)
    out: Dict[str, float] = {"tokens_per_sec": tokens_per_sec}
    if flops_per_token:
        peak = peak_tflops or V5E_BF16_PEAK_TFS
        tfs = model_tflops_per_sec(tokens_per_sec, flops_per_token)
        train_model_tflops_per_sec.set(tfs)
        train_mfu.set(tfs / peak)
        out["model_tflops_per_sec"] = tfs
        out["mfu"] = tfs / peak
    return out


def observe_step(seconds: float, *, phase: str = "run") -> None:
    """One optimizer step's wall time into the step histogram."""
    train_step_seconds.labels(phase=phase).observe(seconds)
    train_steps_total.inc()


def observe_window(n_steps: int, window_seconds: float, *,
                   phase: str = "run") -> None:
    """A timed n-step measurement window (the bench protocol): recorded
    as n observations of the mean step time, so window-level timing and
    the per-step histogram stay one distribution."""
    if n_steps <= 0:
        return
    mean = window_seconds / n_steps
    child = train_step_seconds.labels(phase=phase)
    for _ in range(n_steps):
        child.observe(mean)
    train_steps_total.inc(n_steps)


def step_snapshot() -> Dict[float, float]:
    """Cumulative step-histogram buckets (summed over phases) — pass to
    ``step_quantiles(since=...)`` to diff out earlier work."""
    return tmetrics.histogram_snapshot(train_step_seconds, {})


def step_quantiles(qs=(0.5, 0.99), *,
                   since: Optional[Dict[float, float]] = None,
                   phase: Optional[str] = None):
    """Estimated step-time quantiles, summed over phases unless ``phase``
    narrows it."""
    match = {} if phase is None else {"phase": phase}
    return tmetrics.histogram_quantiles(
        train_step_seconds, match, qs, since=since)


class _StepQuantileCollector:
    """Scrape-time ``train_step_seconds_p50/_p99`` gauges over the run
    phase of the live histogram — live estimates without PromQL, the
    compute analog of bench_scale's reconcile p50/p99 read."""

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        qs = step_quantiles((0.5, 0.99), phase="run")
        for q, name in ((0.5, "train_step_seconds_p50"),
                        (0.99, "train_step_seconds_p99")):
            g = GaugeMetricFamily(
                name, f"Estimated p{int(q * 100)} run-phase step time "
                "(histogram interpolation)")
            if qs.get(q) is not None:
                g.add_metric([], qs[q])
            yield g


registry.register(_StepQuantileCollector())


# -- HBM watermarks -----------------------------------------------------------

# memory_stats() key -> exported kind label.
_MEMORY_KINDS = (
    ("bytes_in_use", "in_use"),
    ("peak_bytes_in_use", "peak"),
    ("bytes_limit", "limit"),
)


def device_memory_snapshot() -> Dict[str, Dict[str, int]]:
    """{device_label: {kind: bytes}} for every device whose backend
    implements memory_stats(); devices returning None (CPU) are simply
    absent.  Never raises — telemetry must not take the workload down."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        kinds = {
            kind: int(stats[key])
            for key, kind in _MEMORY_KINDS if key in stats
        }
        if kinds:
            out[f"{d.platform}:{d.id}"] = kinds
    return out


class _DeviceMemoryCollector:
    """Scrape-time ``device_memory_bytes{device,kind}``: one
    memory_stats() sweep per Prometheus scrape, zero cost on the step
    stream."""

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        fam = GaugeMetricFamily(
            "device_memory_bytes",
            "Accelerator memory by device and kind "
            "(in_use | peak | limit), from jax.Device.memory_stats(); "
            "absent on backends without memory introspection",
            labels=["device", "kind"],
        )
        for dev, kinds in sorted(device_memory_snapshot().items()):
            for kind, val in sorted(kinds.items()):
                fam.add_metric([dev, kind], val)
        yield fam


registry.register(_DeviceMemoryCollector())


def hbm_peak_bytes() -> Optional[int]:
    """Worst peak_bytes_in_use across devices (the bench report's
    ``hbm_peak_bytes``); None when no device reports memory stats."""
    peaks = [k["peak"] for k in device_memory_snapshot().values()
             if "peak" in k]
    return max(peaks) if peaks else None


def free_hbm_bytes() -> Optional[int]:
    """Tightest (limit - in_use) across devices — the budget the
    attention pre-flight estimator checks against.  None when no device
    reports both numbers (CPU): estimation still publishes its gauge,
    only the budget warning is skipped."""
    frees = [
        k["limit"] - k["in_use"]
        for k in device_memory_snapshot().values()
        if "limit" in k and "in_use" in k
    ]
    return min(frees) if frees else None


def note_attention_estimate(estimate_bytes: int, **shape_attrs) -> bool:
    """Publish an attention footprint estimate (gauge) and, when it
    exceeds the budget fraction of free HBM, emit ONE structured warning
    JSON line + counter bump.  Returns True when the warning fired.
    Called from ops/attention.py at trace time — strictly before any
    device allocation for the masked path."""
    attention_mask_bytes_estimate.set(estimate_bytes)
    free = free_hbm_bytes()
    if free is None:
        return False
    budget = ATTENTION_HBM_BUDGET_FRACTION * free
    if estimate_bytes <= budget:
        return False
    attention_mask_budget_warnings_total.inc()
    log.warning(
        "attention footprint over budget: %s",
        json.dumps({
            "event": "attention_mask_budget_exceeded",
            "estimate_bytes": int(estimate_bytes),
            "free_hbm_bytes": int(free),
            "budget_fraction": ATTENTION_HBM_BUDGET_FRACTION,
            "ts": round(time.time(), 3),
            **shape_attrs,
        }, sort_keys=True),
    )
    return True


def attention_estimate_value() -> Optional[float]:
    """Current value of the estimate gauge (None before any attention
    call) — the bench's mask-estimate report line."""
    return registry.get_sample_value("attention_mask_bytes_estimate")


def note_attention_impl(impl: str) -> None:
    """Record which implementation dot_product_attention selected (called
    at trace time from ops/attention.py)."""
    attention_kernel_calls_total.labels(impl=impl).inc()


def attention_impl_calls(impl: str) -> float:
    """Cumulative attention_kernel_calls_total{impl} (0.0 before any call)
    — bench.py snapshot-diffs this per arm to prove the flash arm really
    traced the Pallas kernel."""
    return registry.get_sample_value(
        "attention_kernel_calls_total", {"impl": impl}) or 0.0


def render() -> bytes:
    return tmetrics.render(registry)
