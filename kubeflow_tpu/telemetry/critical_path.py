"""Critical-path decomposition of an object journey.

Input: the span list of one causal journey (telemetry/causal.py — one
trace_id's spans, possibly merged across replicas).  Output: the longest
causal chain through the journey and its decomposition into the named
segments an operator can act on:

========================  ====================================================
segment                   what the time is
========================  ====================================================
``watch_lag``             API write committed → watch event delivered to the
                          controller (stamp wall time → delivery wall time)
``queue_wait``            watch delivery → workqueue dequeue
``reconcile``             reconcile body wall time (minus carved-out children)
``write_rtt``             one child write's round trip inside a reconcile
``admission_queue``       TPUJob queue decision wait (queuedAt → admitted)
``readiness_warm``        controller-side /readyz warm-probe round trip
``pod_start``             a gap on the path right after pod-owning child
                          writes — kubelet territory (image pull, start)
``unattributed``          any other gap on the path (idle between causes)
========================  ====================================================

The chain is reconstructed backwards from the journey's last-ending
span: each step picks the latest-ending span that finished before the
current one began (causes strictly precede effects on one timeline —
reconcile-driven causality has no concurrent-join ambiguity, the API
server serializes it).  Path spans are then EXPANDED: a reconcile span
containing write_rtt / admission_queue / readiness_warm child spans is
split around them, so the decomposition separates reconcile CPU from the
I/O it paid.  Gaps between path spans are attributed (pod_start /
a covering admission_queue wait / unattributed) rather than dropped, so
the segments SUM to the journey's end-to-end wall time by construction —
the property bench_scale's ``*_segments`` keys and the TPUJob
conformance assertion lean on.
"""
from __future__ import annotations

from typing import Dict, List, Optional

EPS = 1e-4          # causal-ordering tolerance (may A precede B?)
TILE_EPS = 1e-9     # tiling tolerance: every positive gap becomes an entry

SEGMENTS = ("watch_lag", "queue_wait", "reconcile", "write_rtt",
            "pod_start", "admission_queue", "readiness_warm")

# Segments that may be carved out of a containing path span (they happen
# INSIDE a reconcile); watch_lag/queue_wait spans of unrelated objects
# merely OVERLAP a reconcile window on the wall clock and must not be
# spliced into it.
_NESTABLE = frozenset({"write_rtt", "admission_queue", "readiness_warm"})

# Child kinds whose creation hands off to the kubelet: a path gap right
# after writing one of these is container start time, not controller
# idleness.
POD_OWNER_KINDS = frozenset({"StatefulSet", "Deployment", "Pod"})


def critical_path(spans: List[dict]) -> List[dict]:
    """The longest causal chain, earliest-first: walk back from the
    last-ending span, each time to the latest-ending span that completed
    before the current one started."""
    spans = [s for s in spans
             if s.get("end_ts") is not None and s.get("start_ts") is not None]
    if not spans:
        return []
    cur = max(spans, key=lambda s: s["end_ts"])
    path = [cur]
    # Visited guard: EPS-tolerant ordering lets two spans within EPS of
    # each other read as MUTUAL predecessors (adjacent sub-100µs writes),
    # and without the guard the walk would alternate between them
    # forever.  Each step must add a new span, so the walk is bounded by
    # the journey size.
    visited = {id(cur)}
    while True:
        preds = [s for s in spans
                 if id(s) not in visited
                 and s["end_ts"] <= cur["start_ts"] + EPS]
        if not preds:
            break
        cur = max(preds, key=lambda s: (s["end_ts"], s["start_ts"]))
        visited.add(id(cur))
        path.append(cur)
    path.reverse()
    return path


def _slice(span: dict, start: float, end: float) -> dict:
    out = dict(span)
    out["start_ts"], out["end_ts"] = start, end
    out["duration_ms"] = round(max(end - start, 0.0) * 1e3, 3)
    return out


def _expand_one(sp: dict, spans: List[dict]) -> List[dict]:
    """Split a path span around the nestable child spans it contains.
    Tail containment is enough (an admission_queue wait may START before
    the reconcile that resolves it): the child's contribution is clipped
    to the container's window."""
    inner = [s for s in spans
             if s is not sp and s.get("segment") in _NESTABLE
             and sp["start_ts"] + EPS < s["end_ts"] <= sp["end_ts"] + EPS]
    if not inner:
        return [dict(sp)]
    inner.sort(key=lambda s: (max(s["start_ts"], sp["start_ts"]),
                              s["end_ts"]))
    out: List[dict] = []
    cursor = sp["start_ts"]
    for s in inner:
        a = max(s["start_ts"], sp["start_ts"], cursor)
        if s["end_ts"] < cursor - TILE_EPS:
            continue  # fully swallowed by an earlier sibling carve-out
        if a > cursor + TILE_EPS:
            out.append(_slice(sp, cursor, a))
        out.append(_slice(s, a, max(s["end_ts"], a)))
        cursor = max(cursor, s["end_ts"])
    if sp["end_ts"] > cursor + TILE_EPS:
        out.append(_slice(sp, cursor, sp["end_ts"]))
    return out


def _wrote_pod_owner(span: dict, spans: List[dict]) -> bool:
    if (span.get("segment") == "write_rtt"
            and span.get("kind") in POD_OWNER_KINDS):
        return True
    return any(s.get("segment") == "write_rtt"
               and s.get("kind") in POD_OWNER_KINDS
               and span["start_ts"] - EPS <= s["end_ts"]
               <= span["end_ts"] + EPS
               for s in spans)


def _gap_segment(prev: Optional[dict], spans: List[dict],
                 gap_start: float, gap_end: float) -> str:
    # A recorded wait span covering the whole gap names it (a Queued
    # TPUJob's poll-to-poll idle time IS admission-queue wait).
    # TILE_EPS, not EPS: with the looser tolerance a ZERO-LENGTH
    # admission span "covered" any sub-EPS gap adjacent to it and the
    # decomposition double-counted the admission segment.
    for s in spans:
        if (s.get("segment") in ("admission_queue", "pod_start")
                and s["start_ts"] <= gap_start + TILE_EPS
                and s["end_ts"] >= gap_end - TILE_EPS):
            return s["segment"]
    if prev is not None and _wrote_pod_owner(prev, spans):
        return "pod_start"
    return "unattributed"


def _merge_contiguous(entries: List[dict]) -> List[dict]:
    """Fold adjacent same-segment path entries into one: a genuinely
    queued admission produces BOTH an attributed gap (the poll-to-poll
    wait) and the span's tail carved into the granting reconcile — the
    same wait, and the 'exactly one admission_queue segment' contract
    counts it once.  Distinct waits (a re-queue after preemption)
    remain separate because other segments sit between them.  Prefers
    the real span's name/attrs over a gap's."""
    out: List[dict] = []
    for e in entries:
        prev = out[-1] if out else None
        if (prev is not None
                and (prev.get("segment") or "unattributed")
                == (e.get("segment") or "unattributed")
                and e["start_ts"] <= prev["end_ts"] + TILE_EPS):
            merged = dict(e if prev["name"] == "gap" else prev)
            merged["start_ts"] = prev["start_ts"]
            merged["end_ts"] = max(prev["end_ts"], e["end_ts"])
            merged["duration_ms"] = round(
                (merged["end_ts"] - merged["start_ts"]) * 1e3, 3)
            out[-1] = merged
        else:
            out.append(e)
    return out


def decompose(spans: List[dict]) -> dict:
    """Critical path + segment decomposition of one journey.  Returns
    ``{"total_s", "segments": {name: seconds}, "path": [entries]}`` where
    the path entries (expanded spans + attributed gaps) tile
    ``[first_start, last_end]`` exactly, so
    ``sum(segments.values()) == total_s``."""
    path = critical_path(spans)
    if not path:
        return {"total_s": 0.0, "segments": {}, "path": []}
    entries: List[dict] = []
    prev_end: Optional[float] = None
    prev_span: Optional[dict] = None
    for sp in path:
        if prev_end is not None and sp["start_ts"] > prev_end + TILE_EPS:
            seg = _gap_segment(prev_span, spans, prev_end, sp["start_ts"])
            entries.append({
                "name": "gap", "segment": seg,
                "start_ts": prev_end, "end_ts": sp["start_ts"],
                "duration_ms": round(
                    (sp["start_ts"] - prev_end) * 1e3, 3),
            })
        entries.extend(_expand_one(sp, spans))
        prev_end = sp["end_ts"] if prev_end is None \
            else max(prev_end, sp["end_ts"])
        prev_span = sp
    entries = _merge_contiguous(entries)
    segments: Dict[str, float] = {}
    for e in entries:
        seg = e.get("segment") or "unattributed"
        segments[seg] = segments.get(seg, 0.0) + max(
            e["end_ts"] - e["start_ts"], 0.0)
    total = path[-1]["end_ts"] - path[0]["start_ts"]
    return {
        "total_s": round(total, 6),
        "segments": {k: round(v, 6) for k, v in sorted(segments.items())},
        "path": entries,
    }


def segment_summary(spans: List[dict]) -> Dict[str, float]:
    """The bench-line payload: decompose() segments rounded for a JSON
    metric line (empty dict on an empty journey)."""
    return {k: round(v, 4)
            for k, v in decompose(spans)["segments"].items()}
