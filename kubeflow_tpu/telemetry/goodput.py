"""Per-tenant TPU goodput accounting: what fraction of the chip-seconds
each profile HELD actually did work (docs/observability.md "The metrics
pipeline").

The ML-systems "goodput" decomposition over the ledger's allocated
chips: the TpuJobQueue grants every admitted gang's chips and the
InferenceService controller declares every replica's (docs/jobs.md
"One quota truth"), so *allocated chip-seconds* per profile namespace
are already watch-state facts.  This module integrates them against
*productive* chip-seconds — training gangs weighted by their ready
workers, serving replicas by their scraped decode-slot occupancy — and
tiles the remainder into a bounded non-goodput decomposition:

    allocated == goodput + queued + restarting + idle     (exactly)

* **queued** — chips granted but not yet working: an admitted gang
  whose pods are still Pending, a serving replica that has not passed
  readiness (cold starts, rollout warms);
* **restarting** — chips held through a gang restart or a two-phase
  preemption drain (the checkpoint tax);
* **idle** — chips on ready workers doing nothing: empty decode slots,
  a Running gang whose workers lost readiness.

The tiling is BY CONSTRUCTION: each workload's instantaneous chips are
decomposed into the four states with explicit clamps before the dt
integration, so the invariant cannot drift however the inputs misbehave
(pinned by test_goodput.py).  Serving occupancy reads the fleet TSDB
with a staleness bound — a dead replica's frozen last sample stops
counting after ``KFT_GOODPUT_STALENESS_SECONDS``, so a killed pod is
never double-counted against its replacement (the ShardedFleet pin).

``tpu_goodput_ratio{profile}`` and ``tpu_chip_seconds_total{profile,
state}`` land in the control-plane registry; ``/debug/goodput`` serves
the cumulative ledger via the single-slot registry pattern.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Iterable, List, Optional

from kubeflow_tpu.platform import config

STATES = ("goodput", "queued", "restarting", "idle")


@dataclasses.dataclass(frozen=True)
class WorkloadUse:
    """One workload's INSTANTANEOUS allocated chips, decomposed.  The
    constructor inputs are clamped by the factories; ``idle`` is always
    the exact remainder."""

    profile: str
    chips: float
    productive: float = 0.0
    queued: float = 0.0
    restarting: float = 0.0

    @property
    def idle(self) -> float:
        return max(0.0, self.chips - self.productive - self.queued
                   - self.restarting)


def job_use(job: dict) -> Optional[WorkloadUse]:
    """A TPUJob's chip decomposition from its watch state, or None when
    it holds no chips (Queued, terminal, invalid)."""
    from kubeflow_tpu.platform.apis import tpujob as jobapi
    from kubeflow_tpu.platform.k8s.types import deep_get

    spec = jobapi.tpu_slice_or_none(job)
    if spec is None:
        return None
    phase = jobapi.phase_of(job)
    if phase in jobapi.TERMINAL_PHASES or phase not in jobapi.HOLDING_PHASES:
        return None
    alloc = jobapi.allocated_slices(job)
    if alloc is None:
        # Pre-queue legacy jobs hold their full spec width once Running.
        if phase != jobapi.PHASE_RUNNING:
            return None
        alloc = spec.num_slices
    chips = float(alloc) * spec.chips
    if chips <= 0:
        return None
    ns = deep_get(job, "metadata", "namespace", default="") or ""
    if phase in (jobapi.PHASE_RESTARTING, jobapi.PHASE_PREEMPTING):
        return WorkloadUse(ns, chips, restarting=chips)
    if phase == jobapi.PHASE_PENDING:
        return WorkloadUse(ns, chips, queued=chips)
    # Running: productive in proportion to ready workers (the gang's own
    # telemetry — status.slices ready/total); the rest is idle.
    ready = total = 0
    for s in deep_get(job, "status", "slices", default=[]) or []:
        ready += int(s.get("ready", 0) or 0)
        total += int(s.get("total", 0) or 0)
    frac = min(max(ready / total, 0.0), 1.0) if total > 0 else 0.0
    return WorkloadUse(ns, chips, productive=chips * frac)


def service_use(svc: dict, *, tsdb=None, at: Optional[float] = None,
                staleness: Optional[float] = None
                ) -> Optional[WorkloadUse]:
    """An InferenceService's chip decomposition: target replicas are the
    declared charge; unready replicas are ``queued`` (cold start /
    rollout warm); ready replicas are productive in proportion to their
    scraped decode-slot occupancy (``serve_decode_slots_active`` /
    ``serve_decode_slots`` from the fleet TSDB, staleness-bounded) and
    idle for the rest.  None when the service holds no chips."""
    from kubeflow_tpu.platform.apis import inferenceservice as svcapi
    from kubeflow_tpu.platform.k8s.types import meta, name_of

    chips = svcapi.chips_of(svc)
    if chips <= 0:
        return None
    ns = meta(svc).get("namespace") or ""
    key = f"{ns}/{name_of(svc)}"
    status = svc.get("status") or {}
    replicas = max(int(status.get("replicas", 0) or 0), 0)
    ready = min(max(int(status.get("readyReplicas", 0) or 0), 0),
                replicas if replicas else 0)
    # Both revisions' widths charge during a rollout (chips_of); the
    # readiness fraction keys off the serving revision's counts — the
    # warming revision's share reads as queued, which is what a warm IS.
    frac_ready = (ready / replicas) if replicas > 0 else 0.0
    ready_chips = chips * frac_ready
    queued = chips - ready_chips
    occ = 0.0
    if tsdb is not None and ready_chips > 0:
        active = sum(v for _l, _ts, v in tsdb.instant(
            "serve_decode_slots_active", {"service": key},
            at=at, staleness=staleness))
        slots = sum(v for _l, _ts, v in tsdb.instant(
            "serve_decode_slots", {"service": key},
            at=at, staleness=staleness))
        if slots > 0:
            occ = min(max(active / slots, 0.0), 1.0)
    productive = ready_chips * occ
    # idle = ready_chips * (1 - occ), by the remainder property.
    return WorkloadUse(ns, chips, productive=productive, queued=queued)


class GoodputAccountant:
    """Integrate instantaneous WorkloadUse decompositions into
    cumulative per-profile chip-second buckets.  ``observe`` is the
    watch-state entrypoint (jobs + services lists → uses → tick); tests
    drive ``tick`` directly with synthetic uses and a fake clock."""

    def __init__(self, *, now=time.time, staleness: Optional[float] = None):
        self.now = now
        self.staleness = (staleness if staleness is not None
                          else config.knob(
                              "KFT_GOODPUT_STALENESS_SECONDS", 60.0, float,
                              doc="serve occupancy samples older than this "
                                  "stop counting toward goodput (a dead "
                                  "replica's frozen series must not)"))
        self._lock = threading.Lock()
        self._last_ts: Optional[float] = None
        # profile -> {state: chip_seconds} (+ "allocated")
        self._acc: Dict[str, Dict[str, float]] = {}

    # -- integration ----------------------------------------------------------

    def observe(self, jobs: Iterable[dict], services: Iterable[dict], *,
                tsdb=None, at: Optional[float] = None) -> None:
        if at is None:
            at = self.now()
        uses: List[WorkloadUse] = []
        for job in jobs or ():
            use = job_use(job)
            if use is not None:
                uses.append(use)
        for svc in services or ():
            use = service_use(svc, tsdb=tsdb, at=at,
                              staleness=self.staleness)
            if use is not None:
                uses.append(use)
        self.tick(uses, at=at)

    def tick(self, uses: Iterable[WorkloadUse],
             at: Optional[float] = None) -> None:
        from kubeflow_tpu.platform.runtime import metrics

        if at is None:
            at = self.now()
        with self._lock:
            last = self._last_ts
            if last is None:
                self._last_ts = at
                return
            if at <= last:
                # A backwards (NTP step) or duplicate timestamp must not
                # move the integration anchor: rewinding it would
                # re-integrate an interval that was already counted.
                return
            self._last_ts = at
            dt = at - last
            per_tick: Dict[str, Dict[str, float]] = {}
            for use in uses:
                # Clamp each named bucket into the remaining allocation
                # IN ORDER so the sum can never exceed chips, then tile
                # the rest as idle — the invariant holds by construction
                # whatever the inputs claim.
                chips = max(use.chips, 0.0)
                queued = min(max(use.queued, 0.0), chips)
                restarting = min(max(use.restarting, 0.0), chips - queued)
                productive = min(max(use.productive, 0.0),
                                 chips - queued - restarting)
                idle = chips - queued - restarting - productive
                buckets = per_tick.setdefault(
                    use.profile, dict.fromkeys(STATES, 0.0))
                buckets["goodput"] += productive
                buckets["queued"] += queued
                buckets["restarting"] += restarting
                buckets["idle"] += idle
            for profile, buckets in per_tick.items():
                acc = self._acc.setdefault(
                    profile, dict.fromkeys((*STATES, "allocated"), 0.0))
                for state in STATES:
                    cs = buckets[state] * dt
                    acc[state] += cs
                    acc["allocated"] += cs
                    if cs > 0:
                        metrics.tpu_chip_seconds_total.labels(
                            profile=profile, state=state).inc(cs)
            for profile, acc in self._acc.items():
                if acc["allocated"] > 0:
                    metrics.tpu_goodput_ratio.labels(profile=profile).set(
                        acc["goodput"] / acc["allocated"])

    # -- introspection --------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/goodput payload: cumulative chip-seconds per
        profile, the ratio, and the tiling check (always True by
        construction — served so a reader can verify, not trust)."""
        with self._lock:
            profiles = {}
            for profile, acc in sorted(self._acc.items()):
                allocated = acc["allocated"]
                profiles[profile] = {
                    "allocatedChipSeconds": round(allocated, 3),
                    **{f"{s}ChipSeconds": round(acc[s], 3) for s in STATES},
                    "goodputRatio": (round(acc["goodput"] / allocated, 4)
                                     if allocated > 0 else None),
                    "tiles": abs(sum(acc[s] for s in STATES)
                                 - allocated) < 1e-6,
                }
            return {"profiles": profiles,
                    "lastTickAt": (round(self._last_ts, 3)
                                   if self._last_ts else None)}


# -- /debug/goodput registry (single-slot, like jobqueue's) -------------------

_debug_accountant: Optional[GoodputAccountant] = None


def register_debug_goodput(acct: Optional[GoodputAccountant]) -> None:
    global _debug_accountant
    _debug_accountant = acct


def debug_snapshot() -> Optional[dict]:
    a = _debug_accountant
    return a.snapshot() if a is not None else None
