"""Always-on sampling profiler: the "why" layer under the SLO stack.

A single daemon thread walks ``sys._current_frames()`` at
``KFT_PROFILE_HZ`` (default 67 Hz — a prime-ish rate so the sampler
doesn't phase-lock with periodic work) and folds every thread's stack
into a bounded ``(thread_role, folded_stack) -> count`` aggregate per
rotating window (a ring of ``KFT_PROFILE_WINDOWS``).  The design is the
Google-Wide Profiling / pprof lineage scaled down to one process: always
on, low single-digit-percent overhead (the bench band
``ctrlplane_profile_overhead_pct`` holds it ≤ 5%), and useful precisely
because it was running *before* anyone knew there was a problem.

Attribution joins each sampled thread against the seams the platform
already maintains, in priority order:

1. **active role** — set by the shared ``telemetry.trace.Tracer`` on
   ``begin``/``adopt``/``finish``: the active reconcile's controller
   (runtime/controller.py), a FlightPool slot carrying a submitted
   reconcile's trace (runtime/flight.py ``adopt``), a serve request
   (telemetry/serve.py), a train step (telemetry/compute.py);
2. **static role** — long-lived pool threads registered at creation
   (``register_thread_role``: FlightPool workers under the pool name,
   the fleetscrape pool);
3. **thread name** with any trailing ``-N``/``_N`` counters stripped
   (``fleet-metrics-pipeline``, ``notebook-worker`` …); interpreter
   default names (``Thread-N``, ``Dummy-N``) mean nobody claimed the
   thread and fold to ``unattributed``.

So a window answers "what was the ``notebook`` reconcile CPU doing
during the 14:02 burn" with a flamegraph, not a guess.  Exports are the
standard folded-stack text (``role;frame;...;frame count`` per line,
root first — feed straight to flamegraph.pl / speedscope), a signed
window diff, and a synchronous on-demand ``capture(seconds)``; all
served at ``/debug/profile`` (platform/main.py, ``DEBUG_TRACES``-gated).
Per-role self-time feeds scrape-time gauges
(``kft_profile_self_seconds`` in runtime/metrics.py) so the TSDB/SLO
layer sees profile-derived signals, and incident bundles
(telemetry/incidents.py) snapshot the covering window at page time.

Like the other debug surfaces this module keeps a process-wide
single-slot registry (``register_debug_profiler``) so HTTP handlers and
the flight recorder can find the live profiler without plumbing.
"""
from __future__ import annotations

import re
import sys
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from kubeflow_tpu.platform import config

# -- thread-role registries ---------------------------------------------------
#
# Module-level dicts keyed by thread ident, each entry carrying a
# weakref to the registering Thread; single-key reads and writes are
# GIL-atomic, and the sampler snapshots via :func:`_live_roles` before
# iterating.  ``_active_roles`` is the dynamic seam (Tracer-driven,
# cleared on finish/adopt(None)); ``_static_roles`` is claimed once at
# thread creation.  The weakref matters: the OS recycles thread idents,
# so "claimed once, lives as long as the thread" must mean the THREAD,
# not the ident — a dead pool worker's entry must never re-attribute an
# unrelated new thread that inherited its ident (and a thread that died
# mid-trace must not leak its active role the same way).

_active_roles: Dict[int, Tuple["weakref.ref", str]] = {}
_static_roles: Dict[int, Tuple["weakref.ref", str]] = {}

_DEFAULT_THREAD_NAME = re.compile(r"^(Thread|Dummy)-\d+")
_NAME_COUNTERS = re.compile(r"([-_]\d+)+$")

UNATTRIBUTED = "unattributed"


def _thread_for(ident: Optional[int]) -> Optional[threading.Thread]:
    if ident is None or ident == threading.get_ident():
        return threading.current_thread()
    for t in threading.enumerate():
        if t.ident == ident:
            return t
    return None


def _live_roles(registry: Dict[int, Tuple["weakref.ref", str]]
                ) -> Dict[int, str]:
    """ident -> role for entries whose registering thread is still the
    live owner of that ident; dead/recycled entries are pruned."""
    live: Dict[int, str] = {}
    for ident, entry in list(registry.items()):
        t = entry[0]()
        if t is None or not t.is_alive() or t.ident != ident:
            # Conditional removal: a new thread re-registering the
            # recycled ident between our snapshot and this prune must
            # not lose its fresh entry.
            if registry.get(ident) is entry:
                registry.pop(ident, None)
        else:
            live[ident] = entry[1]
    return live


def register_thread_role(role: str, ident: Optional[int] = None) -> None:
    """Claim a stable role for a long-lived thread (call from the thread
    itself at creation, or pass its ident).  Pool workers claim their
    pool name here so ``Thread-N`` never defeats profile grouping."""
    t = _thread_for(ident)
    if t is not None and t.ident is not None:
        _static_roles[t.ident] = (weakref.ref(t), role)


def set_active_role(role: Optional[str], ident: Optional[int] = None) -> None:
    """Point the current thread's samples at ``role`` (the Tracer seam:
    the reconciling controller, the serving model, the train component).
    ``None`` clears, same as :func:`clear_active_role`."""
    if role is None:
        clear_active_role(ident)
        return
    t = _thread_for(ident)
    if t is not None and t.ident is not None:
        _active_roles[t.ident] = (weakref.ref(t), role)


def clear_active_role(ident: Optional[int] = None) -> None:
    _active_roles.pop(ident if ident is not None else threading.get_ident(),
                      None)


def _role_from_name(name: str) -> str:
    if not name or _DEFAULT_THREAD_NAME.match(name):
        return UNATTRIBUTED
    return _NAME_COUNTERS.sub("", name) or UNATTRIBUTED


def resolve_role(ident: int, name: str,
                 active: Optional[Dict[int, str]] = None,
                 static: Optional[Dict[int, str]] = None) -> str:
    """Attribution order: active (Tracer) → static (registered at
    creation) → thread name with trailing counters stripped →
    ``unattributed``."""
    role = (active if active is not None
            else _live_roles(_active_roles)).get(ident)
    if role is None:
        role = (static if static is not None
                else _live_roles(_static_roles)).get(ident)
    if role is None:
        role = _role_from_name(name)
    return role


# -- windows ------------------------------------------------------------------


class ProfileWindow:
    """One rotation's bounded ``(role, folded_stack) -> count``
    aggregate.  ``end`` is None while the window is still filling."""

    __slots__ = ("wid", "start", "end", "samples", "stacks")

    def __init__(self, wid: int, start: float):
        self.wid = wid
        self.start = start
        self.end: Optional[float] = None
        self.samples = 0
        self.stacks: Dict[Tuple[str, str], int] = {}

    def index_entry(self) -> dict:
        return {
            "window": self.wid,
            "start": round(self.start, 3),
            "end": None if self.end is None else round(self.end, 3),
            "samples": self.samples,
            "stacks": len(self.stacks),
        }


def _folded_lines(stacks: Dict[Tuple[str, str], int]) -> str:
    return "\n".join(
        f"{role};{stack} {count}"
        for (role, stack), count in sorted(stacks.items()))


class Profiler:
    """The always-on sampler.  Construct once per process, ``start()``,
    and register with :func:`register_debug_profiler`; tests drive
    ``sample_once``/``rotate`` directly with a fake clock."""

    OVERFLOW_FRAME = "<other>"
    TRUNCATED_FRAME = "<truncated>"

    def __init__(self, *, hz: Optional[float] = None,
                 window_seconds: Optional[float] = None,
                 windows: Optional[int] = None,
                 max_stacks: Optional[int] = None,
                 stack_depth: Optional[int] = None,
                 now=time.time):
        self.hz = float(hz if hz is not None else config.knob(
            "KFT_PROFILE_HZ", 67.0, float,
            doc="sampling profiler rate; the sampler thread walks "
                "sys._current_frames() this many times per second"))
        self.window_seconds = float(
            window_seconds if window_seconds is not None else config.knob(
                "KFT_PROFILE_WINDOW_SECONDS", 60.0, float,
                doc="profile window rotation period; /debug/profile?diff "
                    "compares two of these"))
        ring = int(windows if windows is not None else config.knob(
            "KFT_PROFILE_WINDOWS", 8, int,
            doc="closed profile windows kept in the ring (memory bound)"))
        self.max_stacks = int(max_stacks if max_stacks is not None
                              else config.knob(
            "KFT_PROFILE_MAX_STACKS", 512, int,
            doc="distinct (role, stack) aggregates per window; overflow "
                "folds into the per-role <other> bucket"))
        self.stack_depth = int(stack_depth if stack_depth is not None
                               else config.knob(
            "KFT_PROFILE_STACK_DEPTH", 24, int,
            doc="frames kept per sampled stack (leaf-most win; deeper "
                "stacks are marked <truncated> at the root)"))
        self._now = now
        self._lock = threading.Lock()
        self._wid = 0
        self._current: Optional[ProfileWindow] = None
        self._ring: deque = deque(maxlen=max(1, ring))
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._sampler_ident: Optional[int] = None
        self._metric_children: Dict[str, object] = {}
        self.errors = 0
        # CPU burnt by the sampler thread itself (time.thread_time
        # deltas around each pass) — the numerator of the
        # ctrlplane_profile_overhead_pct band, and the honest answer to
        # "what does always-on cost" that wall-clock A/B can't give on a
        # noisy shared container.
        self.sampler_cpu_seconds = 0.0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="kft-profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        self._sampler_ident = threading.get_ident()
        register_thread_role("kft-profiler")
        period = 1.0 / max(self.hz, 0.001)
        while not self._stop_evt.wait(period):
            t0 = time.thread_time()
            try:
                self.sample_once()
            except Exception:
                # Losing one sampling pass is fine; losing the sampler
                # thread is not.  Counted, surfaced via ?list=1.
                self.errors += 1
            finally:
                self.sampler_cpu_seconds += time.thread_time() - t0

    # -- sampling -------------------------------------------------------------

    def _fold(self, frame) -> str:
        parts: List[str] = []
        depth = 0
        truncated = False
        while frame is not None:
            if depth >= self.stack_depth:
                truncated = True
                break
            code = frame.f_code
            fname = code.co_filename
            slash = fname.rfind("/")
            parts.append(f"{fname[slash + 1:]}:{code.co_name}")
            frame = frame.f_back
            depth += 1
        if truncated:
            parts.append(self.TRUNCATED_FRAME)
        parts.reverse()  # root first, the folded-stack convention
        return ";".join(parts)

    def _advance(self, at: float) -> ProfileWindow:
        win = self._current
        if win is None or at >= win.start + self.window_seconds:
            if win is not None:
                win.end = at
                self._ring.append(win)
            self._wid += 1
            win = self._current = ProfileWindow(self._wid, at)
        return win

    def sample_once(self, at: Optional[float] = None) -> int:
        """One sampling pass over every live thread (minus the sampler
        and the caller); returns the number of samples folded in."""
        at = self._now() if at is None else at
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        active = _live_roles(_active_roles)
        static = _live_roles(_static_roles)
        skip = {self._sampler_ident, threading.get_ident()}
        role_counts: Dict[str, int] = {}
        n = 0
        with self._lock:
            win = self._advance(at)
            for ident, frame in frames.items():
                if ident in skip:
                    continue
                role = resolve_role(ident, names.get(ident, ""),
                                    active, static)
                key = (role, self._fold(frame))
                if key not in win.stacks and (
                        len(win.stacks) >= self.max_stacks):
                    key = (role, self.OVERFLOW_FRAME)
                win.stacks[key] = win.stacks.get(key, 0) + 1
                win.samples += 1
                role_counts[role] = role_counts.get(role, 0) + 1
                n += 1
        self._bump_samples(role_counts)
        return n

    def _bump_samples(self, role_counts: Dict[str, int]) -> None:
        if not role_counts:
            return
        try:
            # Lazy: runtime.metrics imports chase prometheus registration
            # order; telemetry modules resolve it at use (the
            # fleetscrape/slo pattern).
            from kubeflow_tpu.platform.runtime import metrics as rt_metrics
        except Exception:
            return
        for role, count in role_counts.items():
            child = self._metric_children.get(role)
            if child is None:
                child = rt_metrics.kft_profile_samples_total.labels(role=role)
                self._metric_children[role] = child
            child.inc(count)

    def rotate(self, at: Optional[float] = None) -> int:
        """Force-close the current window (tests; incident capture keeps
        the *open* window — rotation is time-driven in production).
        Returns the new current window id."""
        at = self._now() if at is None else at
        with self._lock:
            win = self._current
            if win is not None:
                win.end = at
                self._ring.append(win)
            self._wid += 1
            self._current = ProfileWindow(self._wid, at)
            return self._wid

    # -- reads ----------------------------------------------------------------

    def current_window_id(self, at: Optional[float] = None) -> int:
        """Id of the window that covers "now" — what slow-trace dumps and
        incident bundles reference.  Opens the first window if sampling
        has not started yet."""
        at = self._now() if at is None else at
        with self._lock:
            return self._advance(at).wid

    def _find(self, wid: int) -> Optional[ProfileWindow]:
        win = self._current
        if win is not None and win.wid == wid:
            return win
        for w in self._ring:
            if w.wid == wid:
                return w
        return None

    def windows(self) -> List[dict]:
        """Ring index (oldest closed first, open window last) — the
        ``/debug/profile?list=1`` payload."""
        with self._lock:
            out = [w.index_entry() for w in self._ring]
            if self._current is not None:
                out.append(self._current.index_entry())
            return out

    def folded(self, window: Optional[int] = None) -> Optional[str]:
        """Folded-stack text for one window (default: the open one);
        None when the id has aged out of the ring."""
        with self._lock:
            win = self._current if window is None else self._find(window)
            if win is None:
                return None
            return _folded_lines(win.stacks)

    def diff(self, w1: int, w2: int) -> Optional[str]:
        """Signed per-stack sample deltas ``w2 - w1`` ("what got hot"),
        largest regressions first; None when either window is gone."""
        with self._lock:
            a, b = self._find(w1), self._find(w2)
            if a is None or b is None:
                return None
            deltas: Dict[Tuple[str, str], int] = {}
            for key, count in b.stacks.items():
                deltas[key] = count - a.stacks.get(key, 0)
            for key, count in a.stacks.items():
                if key not in b.stacks:
                    deltas[key] = -count
            return "\n".join(
                f"{role};{stack} {delta:+d}"
                for (role, stack), delta in sorted(
                    deltas.items(), key=lambda kv: (-kv[1], kv[0]))
                if delta)

    def capture(self, seconds: float, hz: Optional[float] = None) -> str:
        """Synchronous on-demand capture (``?seconds=N``): sample at
        ``hz`` for ``seconds`` into a standalone aggregate (never enters
        the ring or the counters) and return the folded text."""
        hz = float(hz or self.hz)
        deadline = time.monotonic() + max(0.0, min(float(seconds), 60.0))
        stacks: Dict[Tuple[str, str], int] = {}
        skip = {self._sampler_ident, threading.get_ident()}
        while True:
            frames = sys._current_frames()
            names = {t.ident: t.name for t in threading.enumerate()}
            active = _live_roles(_active_roles)
            static = _live_roles(_static_roles)
            for ident, frame in frames.items():
                if ident in skip:
                    continue
                role = resolve_role(ident, names.get(ident, ""),
                                    active, static)
                key = (role, self._fold(frame))
                if key not in stacks and len(stacks) >= self.max_stacks:
                    key = (role, self.OVERFLOW_FRAME)
                stacks[key] = stacks.get(key, 0) + 1
            if time.monotonic() >= deadline:
                break
            time.sleep(1.0 / max(hz, 0.001))
        return _folded_lines(stacks)

    def self_seconds(self) -> Dict[str, float]:
        """Per-role self time over the open window (samples / hz) — the
        scrape-time ``kft_profile_self_seconds`` gauge source."""
        with self._lock:
            win = self._current
            if win is None:
                return {}
            counts: Dict[str, int] = {}
            for (role, _stack), count in win.stacks.items():
                counts[role] = counts.get(role, 0) + count
        return {role: count / self.hz for role, count in counts.items()}


# -- process-wide debug registration ------------------------------------------
#
# Single-slot, like jobqueue/slo/goodput: /debug/profile and the flight
# recorder read whatever the entrypoint registered; None means the
# surface 404s and slow dumps skip the window reference.

_DEBUG_PROFILER: Optional[Profiler] = None


def register_debug_profiler(p: Optional[Profiler]) -> None:
    global _DEBUG_PROFILER
    _DEBUG_PROFILER = p


def debug_profiler() -> Optional[Profiler]:
    return _DEBUG_PROFILER


def covering_window_id() -> Optional[int]:
    """Window id covering "now" on the registered profiler, or None when
    no profiler runs — the slow-reconcile/slow-step dump reference."""
    p = _DEBUG_PROFILER
    if p is None:
        return None
    try:
        return p.current_window_id()
    except Exception:
        return None
