"""Fleet scrape manager: the one scrape→store path between telemetry
emission and every decision loop (docs/observability.md "The metrics
pipeline").

Before this module, every consumer re-scraped privately: the
InferenceService autoscaler fetched its replicas' /metrics and diffed
TTFT buckets inside the reconciler, bench bands were one-shot, and no
component could ask a HISTORY question ("is the TTFT SLO burning?").
``FleetScraper`` owns the fetch: targets (a URL through the scraper
hook, or an in-process page callable for self-scrapes) fan out on a
dedicated named FlightPool (``scrape_pool``: a slow target must not
starve the controllers' shared pool, and its workers carry a stable
``fleetscrape`` profile role), pages parse ONCE, and every sample lands
in the
:class:`~kubeflow_tpu.telemetry.tsdb.TSDB` carrying the target's labels
plus the one per-pass timestamp that makes pass-joins exact.

Scrape failures are counted with a BOUNDED ``reason`` label —
``timeout`` / ``connect`` / ``parse`` — so an alert can tell a down
replica from a parse regression (the satellite contract the old bare
``inferenceservice_scrape_errors_total`` could not honor).

``serve_sample`` is the autoscaler's migration seam: it computes the
exact :class:`ServeSample` the old private-scrape path produced —
per-replica gauge means, summed counters, TTFT p99 over the merged-
bucket DELTA between this pass and the previous one (first pass and
post-outage passes re-baseline to no signal) — from stored series
alone.  The A/B pin in tests/ctrlplane/test_autoscale.py holds the two
paths sample-identical on the same traffic, which makes the decisions
identical by purity of ``decide_scale``.

``MetricsPipeline`` is the cadence loop platform/main.py runs: scrape
the discovered targets (self-scrape included), evaluate the SLO rules,
tick the goodput accountant — one thread, one knobbed interval.
"""
from __future__ import annotations

import dataclasses
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.telemetry.tsdb import TSDB

log = logging.getLogger("kubeflow_tpu.telemetry.fleetscrape")

SCRAPE_TIMEOUT_S = 2.0
# One record per scrape pass per service: value = replicas that answered.
# serve_sample() joins this pass and the previous one.
PASS_SERIES = "fleetscrape_pass"

_default_tsdb: Optional[TSDB] = None
_default_tsdb_lock = threading.Lock()


def default_tsdb() -> TSDB:
    """The process-wide shared store: the InferenceService reconciler
    (via make_controller) writes its replica scrapes here and the
    manager's rule engine reads the same series — ONE scrape path, one
    history.  Sized through knobs so a large fleet can scale the bounds
    (a store that hits max_series churn-evicts live series and silently
    corrupts burn windows — ``kft_tsdb_series_evicted_total`` is the
    alarm).  Tests that need isolation pass their own TSDB instead."""
    global _default_tsdb
    with _default_tsdb_lock:
        if _default_tsdb is None:
            _default_tsdb = TSDB(
                capacity=config.knob(
                    "KFT_TSDB_CAPACITY", 360, int,
                    doc="samples kept per series in the fleet TSDB "
                        "(ring; ~1.5h at the 15s cadence)"),
                max_series=config.knob(
                    "KFT_TSDB_MAX_SERIES", 8192, int,
                    doc="series bound of the fleet TSDB; exceeding it "
                        "evicts oldest-last-sample series — size for "
                        "targets x series-per-page"))
        return _default_tsdb


@dataclasses.dataclass
class Target:
    """One scrape endpoint: a URL (fetched through the scraper hook) or
    an in-process page callable (``fetch`` — the self-scrape of a local
    registry).  ``labels`` ride every stored sample.  ``names`` (when
    set) stores only those sample names — the fleet-scale guard: a
    serving replica's page carries dozens of series but the decision
    loops read six, and ingesting everything from hundreds of replicas
    would blow the TSDB's series bound into eviction churn."""

    url: Optional[str] = None
    fetch: Optional[Callable[[], Optional[str]]] = None
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    names: Optional[frozenset] = None


@dataclasses.dataclass
class ScrapeStats:
    targets: int = 0
    ok: int = 0
    samples: int = 0
    errors: Dict[str, int] = dataclasses.field(default_factory=dict)


_scrape_pool = None
_scrape_pool_lock = threading.Lock()


def scrape_pool():
    """The fleetscrape fan-out pool: dedicated (never the controllers'
    shared pool — a slow scrape target must not starve reconcile
    fan-outs) and NAMED, so its workers carry a stable ``fleetscrape``
    profile role instead of sampling as Thread-N.  Re-resolved when the
    size knob changes (the shared_pool() pattern)."""
    from kubeflow_tpu.platform.runtime.flight import FlightPool

    global _scrape_pool
    size = config.knob(
        "KFT_FLEETSCRAPE_POOL_SIZE", 8, int,
        doc="worker threads fanning out fleet scrape targets")
    with _scrape_pool_lock:
        if _scrape_pool is None or _scrape_pool.size != size:
            _scrape_pool = FlightPool(size, name="fleetscrape")
        return _scrape_pool


def fetch_url(url: str, timeout: float = SCRAPE_TIMEOUT_S):
    """(text, None) or (None, reason) — the default classified fetcher.
    ``timeout`` = socket-level stall, ``connect`` = everything else that
    kept bytes from arriving (refused, reset, DNS)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace"), None
    except socket.timeout:
        return None, "timeout"
    except urllib.error.URLError as e:
        reason = getattr(e, "reason", None)
        if isinstance(reason, socket.timeout):
            return None, "timeout"
        return None, "connect"
    except (OSError, ValueError):
        return None, "connect"


class FleetScraper:
    """Fan scrapes out, parse once, store with target labels.

    ``scraper``: the single swappable fetch hook (``scraper(url) ->
    text | None``) shared with the InferenceService controller's
    hermetic harnesses — a hook returning None counts as ``connect``
    (the hook cannot say more), a hook raising ``TimeoutError`` as
    ``timeout``; when no hook is given the classified default fetcher
    runs.  ``on_error(reason)`` lets an owner bump its OWN failure
    counter (the serving controller keeps
    ``inferenceservice_scrape_errors_total{reason}``) next to the
    pipeline-wide ``fleetscrape_scrape_errors_total{reason}``.
    """

    def __init__(self, tsdb: Optional[TSDB] = None, *,
                 scraper: Optional[Callable[[str], Optional[str]]] = None,
                 on_error: Optional[Callable[[str], None]] = None,
                 pool=None, now=time.time):
        self.tsdb = tsdb if tsdb is not None else default_tsdb()
        self.scraper = scraper
        self.on_error = on_error
        self.now = now
        self._pool = pool
        self._sources: List[Callable[[], List[Target]]] = []
        self._seen_evictions = tsdb.evictions if tsdb is not None else 0

    # -- discovery ------------------------------------------------------------

    def add_source(self, fn: Callable[[], List[Target]]) -> None:
        """Register a target-discovery hook (called per pass; exceptions
        are logged and skipped — one broken source must not stop the
        pipeline's other targets)."""
        self._sources.append(fn)

    def targets(self) -> List[Target]:
        out: List[Target] = []
        for fn in self._sources:
            try:
                out.extend(fn() or [])
            except Exception:
                log.debug("target source %r failed", fn, exc_info=True)
        return out

    # -- scraping -------------------------------------------------------------

    def _fetch(self, target: Target):
        if target.fetch is not None:
            try:
                return target.fetch(), None
            except TimeoutError:
                return None, "timeout"
            except Exception:
                return None, "connect"
        if target.url is None:
            return None, "connect"
        if self.scraper is not None:
            try:
                return self.scraper(target.url), None
            except TimeoutError:
                return None, "timeout"
            except Exception:
                return None, "connect"
        return fetch_url(target.url)

    def _count_error(self, reason: str) -> None:
        from kubeflow_tpu.platform.runtime import metrics

        metrics.fleetscrape_scrape_errors_total.labels(reason=reason).inc()
        if self.on_error is not None:
            self.on_error(reason)

    def _scrape_one(self, target: Target, ts: float):
        """(ok, samples) for one target; errors classified + counted."""
        text, reason = self._fetch(target)
        if text is None:
            self._count_error(reason or "connect")
            return False, 0
        if not text:
            # An empty page is a live-but-silent target: no samples, and
            # per the legacy parse contract it does not count as scraped.
            return False, 0
        try:
            n = self.tsdb.ingest_page(text, labels=target.labels, ts=ts,
                                      names=target.names)
        except ValueError:
            self._count_error("parse")
            return False, 0
        return True, n

    def scrape(self, targets: Optional[List[Target]] = None,
               ts: Optional[float] = None) -> ScrapeStats:
        """One pass over ``targets`` (default: the discovery sources),
        fanned out on the shared FlightPool, every sample stamped with
        the SAME pass timestamp."""
        from kubeflow_tpu.platform.runtime import metrics

        discovery_pass = targets is None
        if discovery_pass:
            targets = self.targets()
            # The fleet-wide target count is a DISCOVERY-pass fact; a
            # per-service scrape_service call must not stomp it with one
            # service's replica count.
            metrics.fleetscrape_targets.set(len(targets))
        if ts is None:
            ts = self.now()
        stats = ScrapeStats(targets=len(targets))
        if not targets:
            return stats
        pool = self._pool
        if pool is None:
            pool = self._pool = scrape_pool()
        results = pool.run(
            [lambda t=t: self._scrape_one(t, ts) for t in targets],
            return_exceptions=True)
        for res in results:
            if isinstance(res, BaseException):
                log.debug("scrape slot failed", exc_info=res)
                self._count_error("connect")
                continue
            ok, n = res
            if ok:
                stats.ok += 1
                stats.samples += n
        metrics.fleetscrape_samples_total.inc(stats.samples)
        # Surface the store's eviction churn: series evicted at the
        # max_series bound silently lose burn-window history, so the
        # count must be scrapeable, not a buried attribute.
        evictions = self.tsdb.evictions
        if evictions > self._seen_evictions:
            metrics.kft_tsdb_series_evicted_total.inc(
                evictions - self._seen_evictions)
            self._seen_evictions = evictions
        return stats

    def scrape_service(self, key: str, targets: List[Target],
                       ts: Optional[float] = None) -> ScrapeStats:
        """One autoscaler pass for service ``key`` ("ns/name"): scrape
        the replica targets and record the pass (replicas that answered)
        so ``serve_sample`` can join this pass against the previous
        one.  Recorded even at zero targets/answers — an outage pass
        re-baselines the TTFT delta exactly like the legacy path's
        ``_ttft_prev.pop``.

        Pass timestamps are forced strictly monotonic per service: the
        exact-ts pass join must survive callers with coarse (or frozen
        test) clocks — two passes sharing a timestamp would be
        indistinguishable."""
        if ts is None:
            ts = self.now()
        prev = self.tsdb.latest_n(PASS_SERIES, {"service": key}, n=1)
        if prev and ts <= prev[0][0]:
            ts = prev[0][0] + 1e-6
        stats = self.scrape(targets, ts=ts)
        self.tsdb.append(PASS_SERIES, {"service": key}, stats.ok, ts=ts)
        return stats


# -- the autoscaler's stored-series sample ------------------------------------


def serve_sample(tsdb: TSDB, key: str):
    """The :class:`ServeSample` for service ``key`` from stored series —
    the TSDB-backed successor of the reconciler's private
    ``parse_serve_pages`` + ``_ttft_prev`` bucket-delta logic, pinned
    sample-identical by the A/B matrix in test_autoscale.py:

    * gauges (queue depth, slot occupancy) and the request counter come
      from the LATEST pass's exact-timestamp samples (a replica that
      missed the pass contributes nothing);
    * TTFT p99 is computed over ``max(0, cur - prev)`` per ``le`` of the
      pass-merged buckets — so a replica restart (bucket reset) clamps
      to zero instead of going negative, a NEW replica's cumulative
      history counts once (it is absent from the previous merge), and a
      pass with no answering replicas yields no signal and re-baselines
      the next one.
    """
    from kubeflow_tpu.platform.runtime.autoscale import ServeSample
    from kubeflow_tpu.telemetry.metrics import quantile_from_buckets

    passes = tsdb.latest_n(PASS_SERIES, {"service": key}, n=2)
    if not passes:
        return ServeSample()
    pass_ts, replicas = passes[0]
    replicas = int(replicas)
    if replicas <= 0:
        return ServeSample()
    m = {"service": key}

    def _sum(name: str) -> float:
        return sum(v for _labels, v in tsdb.values_at(name, m, pass_ts))

    queue_sum = _sum("serve_queue_depth")
    active_sum = _sum("serve_decode_slots_active")
    slots_sum = _sum("serve_decode_slots")
    requests = _sum("generate_requests_total")
    ttft = None
    if len(passes) > 1 and passes[1][1] > 0:
        prev_ts = passes[1][0]
        cur = tsdb.merged_at("serve_time_to_first_token_seconds_bucket",
                             m, ts=pass_ts)
        prev = tsdb.merged_at("serve_time_to_first_token_seconds_bucket",
                              m, ts=prev_ts)
        delta = {le: max(0.0, c - prev.get(le, 0.0))
                 for le, c in cur.items()}
        ttft = quantile_from_buckets(delta, 0.99)
    return ServeSample(
        replicas_scraped=replicas,
        queue_depth=queue_sum / replicas,
        ttft_p99_s=ttft,
        slot_occupancy=(active_sum / slots_sum) if slots_sum > 0 else None,
        requests_total=requests,
    )


# -- discovery helpers --------------------------------------------------------


def self_target(render: Callable[[], bytes], *,
                labels: Optional[Dict[str, str]] = None) -> Target:
    """Self-scrape of an in-process registry: ``render`` is e.g.
    ``runtime.metrics.render`` — the same exposition text /metrics
    serves, parsed through the same path as any remote page."""

    def fetch() -> str:
        out = render()
        return out.decode() if isinstance(out, bytes) else out

    return Target(fetch=fetch, labels=dict(labels or {}))


def peer_targets() -> List[Target]:
    """Controller-replica peers from the ``KFT_SCRAPE_PEERS`` knob
    (comma-separated health-port base URLs — the Deployment's headless
    service resolves replicas): each peer's /metrics joins the fleet
    store with a ``replica`` label."""
    peers = config.knob(
        "KFT_SCRAPE_PEERS", "", str,
        doc="comma-separated controller health-port base URLs to scrape "
            "into the fleet TSDB (e.g. http://controllers-0:8080)")
    out = []
    for base in [p.strip() for p in peers.split(",") if p.strip()]:
        out.append(Target(url=base.rstrip("/") + "/metrics",
                          labels={"replica": base.rstrip("/")}))
    return out


# The serve series the decision loops actually read: the autoscaler's
# sample (serve_sample), the serve-TTFT burn rule, and goodput's slot
# occupancy.  Replica pages carry much more; at hundreds of replicas
# storing it all would churn the TSDB's series bound — so replica
# targets filter to this set by default.
SERVE_SAMPLE_NAMES = frozenset({
    "serve_queue_depth",
    "serve_decode_slots",
    "serve_decode_slots_active",
    "generate_requests_total",
    "serve_time_to_first_token_seconds_bucket",
    "serve_replica_revision",
})


def inferenceservice_targets(pods: List[dict], *, port: int,
                             service_key: str,
                             names: Optional[frozenset] = SERVE_SAMPLE_NAMES
                             ) -> List[Target]:
    """Replica targets for one InferenceService from its READY pods via
    the existing endpoint contract (the ``inferenceservices.kubeflow.org
    /endpoint`` annotation, else pod IP).  ``names=None`` stores the
    whole page."""
    from kubeflow_tpu.platform.apis.inferenceservice import ANNOTATION_ENDPOINT
    from kubeflow_tpu.platform.k8s.types import deep_get, name_of

    out = []
    for pod in pods:
        override = deep_get(pod, "metadata", "annotations",
                            ANNOTATION_ENDPOINT)
        if override:
            url = override.rstrip("/")
        else:
            ip = deep_get(pod, "status", "podIP")
            url = f"http://{ip}:{port}" if ip else None
        if url is None:
            continue
        out.append(Target(url=url + "/metrics",
                          labels={"service": service_key,
                                  "replica": name_of(pod)},
                          names=names))
    return out


# -- the cadence loop ---------------------------------------------------------


class MetricsPipeline:
    """scrape → store → evaluate on one knobbed cadence
    (``KFT_PIPELINE_INTERVAL_SECONDS``): the thread platform/main.py
    starts next to the controller manager.  Each ``step()`` scrapes the
    discovered targets into the shared TSDB, evaluates the SLO rule
    engine (burn-rate alerts + recording rules), and ticks the goodput
    accountant from watch/list state.  Pure parts stay swappable: tests
    drive ``step()`` directly with a fake clock."""

    def __init__(self, *, tsdb: Optional[TSDB] = None,
                 scraper: Optional[Callable] = None,
                 engine=None, goodput=None, client=None,
                 informers: Optional[dict] = None,
                 interval: Optional[float] = None,
                 incidents=None, now=time.time):
        from kubeflow_tpu.telemetry import goodput as goodput_mod
        from kubeflow_tpu.telemetry import incidents as incidents_mod
        from kubeflow_tpu.telemetry import slo

        self.tsdb = tsdb if tsdb is not None else default_tsdb()
        self.now = now
        self.scraper = FleetScraper(self.tsdb, scraper=scraper, now=now)
        self.engine = (engine if engine is not None
                       else slo.RuleEngine(self.tsdb, slo.default_rules(),
                                           client=client, now=now))
        # The incident flight recorder rides the engine's firing
        # transitions by default (pass ``incidents=False`` to run
        # without one; a caller-built engine keeps its own recorder).
        if incidents is None and self.engine.incidents is None:
            incidents = incidents_mod.IncidentRecorder(
                self.tsdb, client=client, now=now)
        self.incidents = incidents or self.engine.incidents
        if incidents:
            self.engine.incidents = incidents
        self.goodput = (goodput if goodput is not None
                        else goodput_mod.GoodputAccountant(now=now))
        self.client = client
        self.interval = (interval if interval is not None
                         else config.env_float(
                             "KFT_PIPELINE_INTERVAL_SECONDS", 15.0))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Workload feed for the goodput tick: cache-backed lists, never
        # a raw client.list per cadence (exactly the apiserver load
        # informers exist to eliminate).  ``informers`` injects existing
        # UNSHARDED {TPUJOB: Informer, INFERENCESERVICE: Informer}
        # caches (goodput wants the global view — a shard-filtered
        # controller informer would under-count); absent that, start()
        # opens its own pair (one extra LIST+WATCH per kind — the same
        # deliberate side-feed pattern as the controllers' unsharded
        # queue informers).  Direct step() callers (tests, benches)
        # without start() fall back to client lists against their
        # in-memory fakes.
        self._informers: Optional[dict] = informers
        self._owns_informers = False

    def step(self, at: Optional[float] = None) -> ScrapeStats:
        if at is None:
            at = self.now()
        stats = self.scrape(at)
        try:
            self.engine.evaluate(at=at)
        except Exception:
            log.warning("slo rule evaluation failed", exc_info=True)
        self._tick_goodput(at)
        return stats

    def scrape(self, at: float) -> ScrapeStats:
        return self.scraper.scrape(ts=at)

    def _tick_goodput(self, at: float) -> None:
        if self.goodput is None:
            return
        try:
            from kubeflow_tpu.platform.k8s.types import (
                INFERENCESERVICE,
                TPUJOB,
            )

            jobs, services = [], []
            if self._informers is not None:
                # Cache-backed reads (frozen views; goodput only reads).
                jobs = self._informers[TPUJOB].list()
                services = self._informers[INFERENCESERVICE].list()
            elif self.client is not None:
                from kubeflow_tpu.platform.k8s import errors

                try:
                    jobs = self.client.list(TPUJOB, None)
                except errors.ApiError:
                    jobs = []
                try:
                    services = self.client.list(INFERENCESERVICE, None)
                except errors.ApiError:
                    services = []
            self.goodput.observe(jobs, services, tsdb=self.tsdb, at=at)
        except Exception:
            log.warning("goodput tick failed", exc_info=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "MetricsPipeline":
        if self._thread is not None:
            return self
        if self.client is not None and self._informers is None:
            from kubeflow_tpu.platform.k8s.types import (
                INFERENCESERVICE,
                TPUJOB,
            )
            from kubeflow_tpu.platform.runtime.informer import Informer

            self._informers = {
                TPUJOB: Informer(self.client, TPUJOB),
                INFERENCESERVICE: Informer(self.client, INFERENCESERVICE),
            }
            self._owns_informers = True
            for informer in self._informers.values():
                informer.start()
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval):
                try:
                    self.step()
                except Exception:
                    log.warning("pipeline step failed", exc_info=True)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="fleet-metrics-pipeline")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)
        if self._owns_informers:
            informers, self._informers = self._informers, None
            self._owns_informers = False
            for informer in (informers or {}).values():
                informer.stop()
