"""Incident flight recorder: turn a page from "go look" into "here is
the evidence".

When a burn-rate alert transitions to firing (telemetry/slo.py's
``RuleEngine``), the evidence an operator needs is scattered across
eight live ``/debug/`` surfaces — and it ages out of ring buffers while
the page is still in flight.  ``IncidentRecorder.capture()`` snapshots
all of it at transition time into ONE bounded, deterministic bundle:

* the offending rule + its live burn rates,
* the TSDB window around the burn (the rule's bucket series over its
  slow window — replayable through the quantile/burn math offline),
* the SLO's recorded series (RecordingRule outputs, when the engine
  carries any),
* merged causal journeys for the worst objects in the burn window
  (telemetry/causal.py's span store, top-K traces by span duration),
* the covering profile window (telemetry/profiler.py — the flamegraph
  of what the process was doing during the burn),
* the live ``/debug/queue`` + ``/debug/goodput`` + alert snapshots, any
  entrypoint-wired extras (``/debug/shards``), and the effective knob
  state (``config.effective()``).

Bundles land in a bounded ring (``KFT_INCIDENT_RING``), debounced per
alert (``KFT_INCIDENT_DEBOUNCE_SECONDS`` — a flapping alert must not
churn the ring), listed by manifest at ``/debug/incidents`` and fetched
whole at ``/debug/incidents/<id>``.  Each capture is announced by
exactly one fleet-wide Event through the stamping apply helpers: name
and owned content are deterministic in the alert alone (burn numbers
would defeat the cross-replica content-hash dedup), so N replicas
observing the same transition emit ONE ``kft-incident-<alert>`` object
— the same discipline as the alert Events themselves.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.telemetry.tsdb import TSDB

log = logging.getLogger("kubeflow_tpu.telemetry.incidents")


class IncidentRecorder:
    """Capture-on-page evidence bundles for one process.  Attach to a
    ``RuleEngine`` (``engine.incidents = recorder`` — MetricsPipeline
    wires this by default) and register with
    :func:`register_debug_incidents` to serve ``/debug/incidents``."""

    def __init__(self, tsdb: TSDB, *, client=None,
                 namespace: str = "kubeflow",
                 component: str = "incident-recorder",
                 ring: Optional[int] = None,
                 debounce_s: Optional[float] = None,
                 max_journeys: Optional[int] = None,
                 max_series: Optional[int] = None,
                 max_samples: Optional[int] = None,
                 now=time.time):
        self.tsdb = tsdb
        self.client = client
        self.namespace = namespace
        self.component = component
        self.now = now
        self.ring = int(ring if ring is not None else config.knob(
            "KFT_INCIDENT_RING", 16, int,
            doc="incident bundles kept in the flight-recorder ring"))
        self.debounce_s = float(
            debounce_s if debounce_s is not None else config.knob(
                "KFT_INCIDENT_DEBOUNCE_SECONDS", 300.0, float,
                doc="minimum seconds between captures of the same alert "
                    "(a flapping alert must not churn the ring)"))
        self.max_journeys = int(
            max_journeys if max_journeys is not None else config.knob(
                "KFT_INCIDENT_JOURNEYS", 3, int,
                doc="worst-object causal journeys snapshotted per bundle"))
        self.max_series = int(
            max_series if max_series is not None else config.knob(
                "KFT_INCIDENT_SERIES", 64, int,
                doc="TSDB series kept per incident bundle export"))
        self.max_samples = int(
            max_samples if max_samples is not None else config.knob(
                "KFT_INCIDENT_SAMPLES", 240, int,
                doc="newest samples kept per exported incident series"))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, self.ring))
        self._last_capture: Dict[str, float] = {}
        # Entrypoint-wired extra snapshot sections (e.g. main.py adds
        # "shards" when sharded HA runs); each callable returns a
        # JSON-able snapshot or None to skip.
        self._sections: Dict[str, Callable[[], Optional[dict]]] = {}

    def add_section(self, name: str,
                    fn: Callable[[], Optional[dict]]) -> None:
        self._sections[name] = fn

    # -- capture --------------------------------------------------------------

    def capture(self, rule, st, at: Optional[float] = None, *,
                engine=None) -> Optional[dict]:
        """Snapshot one bundle for ``rule``'s firing transition at
        ``at``; returns the bundle, or None when debounced.  Everything
        in the bundle is a deterministic function of (rule, at, shared
        state) so sibling replicas produce equivalent manifests."""
        at = self.now() if at is None else at
        with self._lock:
            last = self._last_capture.get(rule.name)
            if last is not None and at - last < self.debounce_s:
                return None
            self._last_capture[rule.name] = at

        start = at - rule.slow_window_s
        bundle = {
            "id": f"{rule.name}-{int(at)}",
            "alert": self._alert_section(rule, st, at),
            "tsdb": self._tsdb_section(rule.metric, dict(rule.matcher),
                                       start, at),
            "journeys": self._journey_section(start, at),
            "profile": self._profile_section(),
            "knobs": config.effective(),
        }
        recorded = self._recorded_section(engine, start, at)
        if recorded:
            bundle["recorded"] = recorded
        for name, snap in self._snapshot_sections(engine).items():
            bundle[name] = snap
        bundle["manifest"] = self._manifest(bundle, rule, at)

        with self._lock:
            self._ring.append(bundle)
        self._bump_metric(rule.name)
        self._emit_event(rule)
        return bundle

    def _alert_section(self, rule, st, at: float) -> dict:
        return {
            "alert": rule.name,
            "state": st.state,
            "capturedAt": round(at, 3),
            "metric": rule.metric,
            "thresholdSeconds": rule.threshold,
            "objective": rule.objective,
            "fastBurn": (round(st.fast_burn, 3)
                         if st.fast_burn is not None else None),
            "slowBurn": (round(st.slow_burn, 3)
                         if st.slow_burn is not None else None),
            "windows": {"fastSeconds": rule.fast_window_s,
                        "slowSeconds": rule.slow_window_s},
            "doc": rule.doc,
        }

    def _export(self, metric: str, matcher: dict,
                start: float, end: float) -> List[dict]:
        series = []
        for labels, samples in self.tsdb.window(metric, matcher,
                                                start, end):
            series.append({
                "labels": dict(sorted(labels.items())),
                "samples": [[round(ts, 6), value]
                            for ts, value in samples[-self.max_samples:]],
            })
        series.sort(key=lambda s: sorted(s["labels"].items()))
        return series[:self.max_series]

    def _tsdb_section(self, metric: str, matcher: dict,
                      start: float, end: float) -> dict:
        return {
            "metric": metric,
            "matcher": dict(sorted(matcher.items())),
            "start": round(start, 3),
            "end": round(end, 3),
            "series": self._export(metric, matcher, start, end),
        }

    def _recorded_section(self, engine, start: float,
                          end: float) -> List[dict]:
        if engine is None or not getattr(engine, "recording", None):
            return []
        return [self._tsdb_section(rec.record, dict(rec.matcher),
                                   start, end)
                for rec in engine.recording]

    def _journey_section(self, start: float, end: float) -> List[dict]:
        """Merged causal journeys for the worst objects of the burn
        window: group in-window spans by trace, rank traces by their
        longest span, keep the top K, export each trace's full
        journey."""
        from kubeflow_tpu.telemetry import causal

        worst: Dict[str, float] = {}
        for span in causal.STORE.recent(start=start, end=end):
            tid = span["trace_id"]
            worst[tid] = max(worst.get(tid, 0.0), span["duration_ms"])
        ranked = sorted(worst.items(), key=lambda kv: (-kv[1], kv[0]))
        out = []
        for tid, duration_ms in ranked[:self.max_journeys]:
            out.append({
                "trace_id": tid,
                "worst_span_ms": duration_ms,
                "spans": causal.merge_journeys(causal.journey(tid)),
            })
        return out

    def _profile_section(self) -> Optional[dict]:
        from kubeflow_tpu.telemetry import profiler

        p = profiler.debug_profiler()
        if p is None:
            return None
        wid = p.current_window_id()
        return {"window": wid, "folded": p.folded(),
                "selfSeconds": {role: round(s, 3) for role, s
                                in sorted(p.self_seconds().items())}}

    def _snapshot_sections(self, engine) -> Dict[str, Optional[dict]]:
        from kubeflow_tpu.platform.runtime import jobqueue
        from kubeflow_tpu.telemetry import goodput

        out: Dict[str, Optional[dict]] = {
            "queue": jobqueue.debug_snapshot(),
            "goodput": goodput.debug_snapshot(),
            "alerts": engine.snapshot() if engine is not None else None,
        }
        for name, fn in sorted(self._sections.items()):
            try:
                out[name] = fn()
            except Exception:
                log.debug("incident section %s failed", name,
                          exc_info=True)
                out[name] = None
        return out

    def _manifest(self, bundle: dict, rule, at: float) -> dict:
        """The ``/debug/incidents`` listing row: deterministic in (rule,
        at, shared state) so sibling replicas list equivalent evidence."""
        profile = bundle.get("profile")
        return {
            "id": bundle["id"],
            "alert": rule.name,
            "state": "firing",
            "capturedAt": int(at),
            "sections": sorted(k for k, v in bundle.items()
                               if k not in ("id", "manifest")
                               and v is not None),
            "series": len(bundle["tsdb"]["series"]),
            "journeys": len(bundle["journeys"]),
            "profileWindow": (profile or {}).get("window"),
        }

    def _bump_metric(self, alert: str) -> None:
        try:
            from kubeflow_tpu.platform.runtime import metrics
        except Exception:
            return
        metrics.kft_incidents_captured_total.labels(alert=alert).inc()

    def _emit_event(self, rule) -> None:
        """Announce the capture fleet-wide: exactly one Event object per
        alert through the stamping apply helpers — deterministic name
        AND owned content (no burn numbers, no bundle ids with replica-
        local clocks in the message) make the sibling replica's apply a
        no-op and a create race land on AlreadyExists."""
        if self.client is None:
            return
        from kubeflow_tpu.platform.k8s import errors
        from kubeflow_tpu.platform.k8s.types import EVENT
        from kubeflow_tpu.platform.runtime.apply import create_or_update

        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": f"kft-incident-{rule.name}",
                         "namespace": self.namespace},
            "involvedObject": {"kind": "FleetSLO", "name": rule.name,
                               "namespace": self.namespace},
            "type": "Warning",
            "reason": "IncidentCaptured",
            "message": (f"incident bundle captured for burn-rate alert "
                        f"{rule.name}; evidence at /debug/incidents on "
                        f"each replica"),
            "source": {"component": self.component},
        }
        try:
            create_or_update(
                self.client, EVENT, ev,
                owned_fields=("type", "reason", "message",
                              "involvedObject", "source"))
        except errors.AlreadyExists:
            pass  # a sibling replica announced this incident first
        except errors.ApiError:
            log.debug("incident event emission failed", exc_info=True)

    # -- reads ----------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/incidents payload: manifests, newest first."""
        with self._lock:
            manifests = [b["manifest"] for b in reversed(self._ring)]
        return {"incidents": manifests, "ring": self.ring,
                "debounceSeconds": self.debounce_s}

    def get(self, incident_id: str) -> Optional[dict]:
        """One full bundle (the /debug/incidents/<id> payload)."""
        with self._lock:
            for b in self._ring:
                if b["id"] == incident_id:
                    return b
        return None


# -- /debug/incidents registry (single-slot, like jobqueue's) -----------------

_debug_recorder: Optional[IncidentRecorder] = None


def register_debug_incidents(rec: Optional[IncidentRecorder]) -> None:
    global _debug_recorder
    _debug_recorder = rec


def debug_snapshot() -> Optional[dict]:
    r = _debug_recorder
    return r.snapshot() if r is not None else None


def debug_get(incident_id: str) -> Optional[dict]:
    r = _debug_recorder
    return r.get(incident_id) if r is not None else None
