"""Shared Prometheus plumbing: the registry-hygiene contract + histogram
quantile estimation, one implementation for both planes.

Registry hygiene (the PR-1 rule, now repo-wide): every kubeflow_tpu
series lives in a **module-local** (or per-app) ``CollectorRegistry``,
never ``prometheus_client.REGISTRY`` — the process-global default stacks
duplicate collectors on test reimports.  Pinned for the control plane by
``tests/ctrlplane/test_metrics.py::test_no_kubeflow_metrics_in_global_registry``
(which now also covers the compute registry) — any new metrics module
should build on ``new_registry()`` and land there too.

The quantile helpers are the bench/report seam: ``bench_scale.py`` reads
reconcile p50/p99 and ``bench.py`` reads step p50/p99 from live
histograms through these functions, so a report line and a /metrics
scrape can never disagree about what was measured.
"""
from __future__ import annotations

from typing import Dict, Optional

from prometheus_client import CollectorRegistry, generate_latest


def new_registry() -> CollectorRegistry:
    """A fresh module-local registry (the only sanctioned home for
    kubeflow_tpu collectors)."""
    return CollectorRegistry()


def render(registry: CollectorRegistry) -> bytes:
    """Prometheus exposition text for a registry (the /metrics body)."""
    return generate_latest(registry)


def histogram_snapshot(hist, match: Dict[str, str]) -> Dict[float, float]:
    """Cumulative bucket counts by upper bound for the children of
    ``hist`` whose labels are a superset of ``match`` — summed over
    non-matched labels (e.g. over ``result`` for the reconcile histogram,
    over ``phase`` for the train-step histogram)."""
    buckets: Dict[float, float] = {}
    for metric in hist.collect():
        for s in metric.samples:
            if not s.name.endswith("_bucket"):
                continue
            if not all(s.labels.get(k) == v for k, v in match.items()):
                continue
            le = float(s.labels["le"])
            buckets[le] = buckets.get(le, 0.0) + s.value
    return buckets


def quantile_from_buckets(buckets: Dict[float, float], q: float) -> Optional[float]:
    """Prometheus-style linear interpolation within the target bucket.
    Returns None on an empty histogram; the +Inf bucket clamps to the
    highest finite bound (same as histogram_quantile)."""
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_count = 0.0, 0.0
    finite = [b for b in bounds if b != float("inf")]
    for b in bounds:
        count = buckets[b]
        if count >= rank:
            if b == float("inf"):
                return finite[-1] if finite else None
            if count == prev_count:
                return b
            return prev_bound + (b - prev_bound) * (
                (rank - prev_count) / (count - prev_count)
            )
        prev_bound, prev_count = (0.0 if b == float("inf") else b), count
    return finite[-1] if finite else None


def histogram_quantiles(hist, match: Dict[str, str], qs=(0.5, 0.99), *,
                        since: Optional[Dict[float, float]] = None
                        ) -> Dict[float, Optional[float]]:
    """Estimated latency quantiles for one histogram slice.  ``since``
    (a prior histogram_snapshot) diffs out observations from earlier runs
    in the same process — the bench protocol for per-arm/per-wave
    reporting."""
    buckets = histogram_snapshot(hist, match)
    if since is not None:
        buckets = {le: c - since.get(le, 0.0) for le, c in buckets.items()}
    return {q: quantile_from_buckets(buckets, q) for q in qs}
