"""Serve-path telemetry: per-request metrics + spans for the generation
services (models/serve.py).

One ``ServeTelemetry`` per app registry (the per-app-registry pattern —
one process can serve several models/tests without duplicate-timeseries
collisions), attached to a service as ``service.telemetry``.  The
request lifecycle maps to spans

    admit (validate + right-pad) → queue (service-lock wait) →
    prefill (prompt pass, ends when the FIRST token is on host) →
    decode (the scan + device→host fetch)

served by ``/debug/traces`` exactly like the control plane's reconcile
traces; TTFT is observed at the prefill span's close (arrival → first
token host-visible), per-token latency as decode seconds per generated
token.

Under the continuous-batching scheduler (models/scheduler.py, the
default instrumented decoder-only path) the same span names map onto the
scheduler lifecycle — queue = submit → admission, prefill = the
admission prompt pass, decode = slot residency — and the series become
the scheduler's tuning loop: ``serve_queue_depth`` gauges PENDING
SCHEDULER QUEUE ROWS (not lock waiters), ``serve_batch_fill_ratio``
observes per-step decode-slot occupancy, and the admitted/evicted
counters balance against ``serve_decode_slots_active``
(admitted == evicted + active, the serve-soak CI invariant).  The
lock-serialized fallback path (KFT_SERVE_SCHEDULER=0, seq2seq) keeps the
original semantics: queue depth counts lock waiters, fill ratio is
request rows over max_batch_rows.
"""
from __future__ import annotations

import itertools
from contextlib import nullcontext
from typing import Optional

from prometheus_client import Counter, Gauge, Histogram

from kubeflow_tpu.platform import config
from kubeflow_tpu.telemetry.trace import Tracer

# Requests at or above this wall time dump their span tree as one JSON
# log line (kubeflow_tpu.serve.trace logger).  Env-tunable; tests set the
# module attribute directly.
SLOW_REQUEST_SECONDS = config.env_float("SERVE_SLOW_REQUEST_SECONDS", 30.0)

_LATENCY_BUCKETS = (0.01, 0.05, 0.2, 1.0, 5.0, 20.0, 60.0, 180.0)
_TOKEN_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

_request_ids = itertools.count(1)


class ServeTelemetry:
    """Instruments + tracer for one serving app.  Every method is safe to
    call from concurrent request threads; a service whose ``telemetry``
    is None skips all of it (the library-use path)."""

    def __init__(self, registry, *, component: str = "model-serve"):
        self.component = component
        self.tracer = Tracer(
            component, keys=("component", "request"),
            buffer_size=config.env_int("SERVE_TRACE_BUFFER_SIZE", 64),
            logger="kubeflow_tpu.serve.trace",
            slow_message="slow serve request trace",
        )
        self.queue_depth = Gauge(
            "serve_queue_depth",
            "Prompt rows pending in the continuous-batching scheduler "
            "queue (not yet holding a decode slot); on the lock-"
            "serialized fallback path, requests waiting on the "
            "generation lock",
            registry=registry,
        )
        self.batch_rows = Histogram(
            "serve_batch_rows", "Rows admitted per generation request",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128), registry=registry,
        )
        self.batch_fill_ratio = Histogram(
            "serve_batch_fill_ratio",
            "Per-decode-step slot occupancy under the scheduler (active "
            "slots over the pool size, observed once per decode "
            "quantum); on the lock path, request rows over "
            "max_batch_rows",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
            registry=registry,
        )
        self.scheduler_admitted = Counter(
            "serve_scheduler_admitted_rows_total",
            "Prompt rows admitted into the decode slot pool (prefilled "
            "and scheduled for decoding)",
            registry=registry,
        )
        self.scheduler_evicted = Counter(
            "serve_scheduler_evicted_rows_total",
            "Rows evicted from the slot pool (EOS or budget exhausted); "
            "admitted == evicted + serve_decode_slots_active at all "
            "times",
            registry=registry,
        )
        self.slots_active = Gauge(
            "serve_decode_slots_active",
            "Decode slots currently occupied by in-flight rows",
            registry=registry,
        )
        self.slots_total = Gauge(
            "serve_decode_slots",
            "Decode slot pool size (KFT_SERVE_SLOTS)",
            registry=registry,
        )
        self.ttft = Histogram(
            "serve_time_to_first_token_seconds",
            "Request arrival to the first generated token host-visible "
            "(admit + queue wait + prefill; includes any compile)",
            buckets=_LATENCY_BUCKETS, registry=registry,
        )
        self.per_token = Histogram(
            "serve_per_token_seconds",
            "Decode seconds per generated token past the first (one "
            "observation per request)",
            buckets=_TOKEN_BUCKETS, registry=registry,
        )
        self.input_tokens = Counter(
            "serve_input_tokens_total", "Prompt/source tokens received",
            registry=registry,
        )
        self.output_tokens = Counter(
            "serve_output_tokens_total",
            "Tokens generated (counted through the first EOS per row, "
            "excluding post-EOS padding)",
            registry=registry,
        )
        # Paged-KV engine (models/paged.py).  Balance invariants, pinned
        # by test_telemetry: free + active + shared == pages_total - 1
        # (the null page is outside every state) at all times, active
        # returns to 0 when the pool drains, and accepted <= proposed.
        self.kv_pages = Gauge(
            "serve_kv_pages",
            "Physical KV pages by state under the paged pool: free (in "
            "the allocator), active (held by live/pending rows only), "
            "shared (resident in the prefix cache); the reserved null "
            "page is counted in none of them",
            ["state"], registry=registry,
        )
        self.kv_page_fragmentation = Gauge(
            "serve_kv_page_fragmentation_ratio",
            "Reserved-but-unwritten fraction of live rows' paged-KV "
            "capacity (0 = every reserved page position holds a real "
            "token; the fixed-slot pool's longest-bucket tax made "
            "visible)",
            registry=registry,
        )
        self.prefix_cache_hits = Counter(
            "serve_prefix_cache_hits_total",
            "Prompt pages served read-only from the prefix cache "
            "instead of prefilling",
            registry=registry,
        )
        self.prefix_cache_misses = Counter(
            "serve_prefix_cache_misses_total",
            "Lookup-eligible prompt pages that had to prefill (no "
            "cached prefix page matched)",
            registry=registry,
        )
        self.spec_proposed = Counter(
            "serve_spec_decode_proposed_tokens_total",
            "Draft-model tokens proposed across speculative-decoding "
            "steps",
            registry=registry,
        )
        self.spec_accepted = Counter(
            "serve_spec_decode_accepted_tokens_total",
            "Proposed draft tokens accepted by target-model "
            "verification (accepted <= proposed; the bonus token per "
            "step is not counted)",
            registry=registry,
        )
        # Sharded paged serving + pipelined dispatch (ISSUE 20).
        self.page_pool_shards = Gauge(
            "serve_page_pool_shards",
            "Shards the paged-KV pool axis splits into over the serving "
            "mesh's data axes (1 = unsharded/replicated pool; set when "
            "the pool is built)",
            registry=registry,
        )
        self.dispatch_overlap = Gauge(
            "serve_dispatch_overlap_ratio",
            "Fraction of each decode dispatch->harvest cycle the "
            "scheduler host thread was NOT blocked on device results "
            "(cumulative since start; the synchronous loop spends the "
            "whole quantum blocked, pipelined dispatch hides the wait "
            "behind bookkeeping)",
            registry=registry,
        )
        self.paged_fallback = Counter(
            "serve_paged_fallback_total",
            "Times the service routed to the fixed-slot scheduler "
            "instead of the paged engine, by structured reason "
            "(env-disabled = KFT_SERVE_PAGED=0, spec-decode-mesh = "
            "draft model under a mesh); /debug/serve carries the "
            "human-readable detail",
            ["reason"], registry=registry,
        )

    # -- request lifecycle ----------------------------------------------------

    def begin_request(self):
        tr = self.tracer.begin(
            self.component, f"req-{next(_request_ids)}")
        if tr is not None:
            # A traceparent header installed by the app (models/serve.py)
            # links this request trace into the caller's causal journey.
            from kubeflow_tpu.telemetry import causal

            ctx = causal.current()
            if ctx is not None:
                tr.links["causal_trace_id"] = ctx.trace_id
                tr.links["causal_span_id"] = ctx.span_id
        return tr

    def span(self, name: str, **attrs):
        return self.tracer.span(name, **attrs)

    def finish_request(self, result: str) -> Optional[dict]:
        return self.tracer.finish(
            result, slow_seconds=SLOW_REQUEST_SECONDS)


def span_or_null(tel: Optional[ServeTelemetry], name: str, **attrs):
    """A telemetry span, or a no-op when the service runs un-instrumented
    (direct library use)."""
    return tel.span(name, **attrs) if tel is not None else nullcontext()
