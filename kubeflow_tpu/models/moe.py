"""Mixture-of-experts MLP with expert parallelism, GShard/Switch style.

The reference platform ships no model code at all (SURVEY.md §2.13); MoE is
part of this stack's compute layer so the v5e-16 pjit flagship config has an
expert-parallel variant.  TPU-first design decisions:

* **Dense one-hot dispatch** (einsums over a [tokens, experts, capacity]
  mask) instead of gather/scatter: every op is a large static-shape matmul
  or mask product that XLA tiles onto the MXU.  No dynamic shapes, no
  sorting networks.
* **Experts live in one batched param tensor** ``(n_experts, ...)`` sharded
  ``P("ep", ...)``; the dispatch einsum's output carries the expert axis, so
  sharding propagation turns token movement into a single XLA all-to-all
  over the ``ep`` mesh axis (ICI), exactly the GShard lowering.
* **Capacity-factor truncation** keeps shapes static: each expert processes
  at most ``capacity`` tokens per group; overflow tokens fall through the
  residual connection (standard Switch behavior).
* The router runs in f32 (softmax stability) regardless of model dtype.

The load-balancing auxiliary loss is sowed into the ``"losses"`` collection
as ``moe_aux_loss``; ``kubeflow_tpu.train.steps.make_lm_train_step`` picks it
up when ``aux_loss_weight > 0``.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """Best-effort sharding constraint via the ambient mesh (no-op without
    one, or on meshes with no ep axis to dispatch over)."""
    from kubeflow_tpu.parallel.context import get_global_mesh
    from kubeflow_tpu.parallel.sharding import constrain

    mesh = get_global_mesh()
    if mesh is None or "ep" not in mesh.axis_names:
        return x
    return constrain(x, spec)


class MoeMlp(nn.Module):
    """Top-k routed SwiGLU experts over a batched expert weight tensor."""

    n_experts: int
    hidden_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array,
                 token_mask: jax.Array = None) -> jax.Array:
        """``token_mask`` [b, s] (True = real token) excludes padding from
        routing: pad tokens consume no expert capacity and contribute
        nothing to the aux loss, so a right-padded batch routes its real
        tokens the same way regardless of padding (exactly equal when
        capacity truncation doesn't bite — capacity itself is static in the
        padded length)."""
        b, s, d = x.shape
        e, k, f = self.n_experts, self.top_k, self.hidden_dim
        # Per-group capacity: each batch row is a routing group, so capacity
        # stays local and the dispatch tensors shard cleanly on the data axes.
        capacity = max(1, int(s * k * self.capacity_factor / e))

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32, name="router")
        logits = router(x.astype(jnp.float32))  # [b, s, e]
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k expert choice per token, k one-hot masks [b, s, e].
        _, topk_idx = jax.lax.top_k(probs, k)  # [b, s, k]
        onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.float32)  # [b, s, k, e]
        if token_mask is not None:
            onehot = onehot * token_mask.astype(jnp.float32)[:, :, None, None]

        # Position of each (token, choice) in its expert's buffer, counted in
        # routing order along the sequence; beyond-capacity slots are dropped.
        flat = onehot.reshape(b, s * k, e)
        pos = jnp.cumsum(flat, axis=1) - flat  # [b, s*k, e]
        pos = pos.reshape(b, s, k, e)
        keep = (pos < capacity) * onehot  # [b, s, k, e]
        pos_oh = jax.nn.one_hot(
            pos.astype(jnp.int32), capacity, dtype=jnp.float32
        )  # [b,s,k,e,c]

        # dispatch[b,s,e,c] ∈ {0,1}; combine carries the router prob.
        dispatch = jnp.einsum("bske,bskec->bsec", keep, pos_oh)
        gates = jnp.einsum("bse,bske->bsk", probs, keep)
        combine = jnp.einsum("bsk,bske,bskec->bsec", gates, keep, pos_oh)

        # Aux load-balancing loss (Switch eq. 4): e * Σ_e f_e · p̄_e,
        # averaged over real tokens only.
        if token_mask is not None:
            w = token_mask.astype(jnp.float32)[:, :, None]  # [b, s, 1]
            denom = jnp.maximum(w.sum(), 1.0)
            token_frac = (onehot.sum(2) * w).sum(axis=(0, 1)) / denom
            prob_frac = (probs * w).sum(axis=(0, 1)) / denom
        else:
            token_frac = jnp.mean(onehot.sum(2), axis=(0, 1))  # [e]
            prob_frac = jnp.mean(probs, axis=(0, 1))  # [e]
        aux = e * jnp.sum(token_frac * prob_frac) / k
        self.sow("losses", "moe_aux_loss", aux)

        # Token movement: [b, s, d] → expert buffers [e, b, c, d].  With x on
        # the data axes and the output constrained to P("ep", ...), XLA
        # lowers this einsum to an all-to-all over the ep axis.
        xin = jnp.einsum(
            "bsec,bsd->ebcd", dispatch.astype(self.dtype), x.astype(self.dtype)
        )
        xin = _constrain(xin, P("ep", ("dp", "fsdp"), None, None))

        w_gate = self.param(
            "w_gate", nn.initializers.lecun_normal(), (e, d, f), jnp.float32
        ).astype(self.dtype)
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(), (e, d, f), jnp.float32
        ).astype(self.dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(), (e, f, d), jnp.float32
        ).astype(self.dtype)

        h = nn.silu(jnp.einsum("ebcd,edf->ebcf", xin, w_gate)) * jnp.einsum(
            "ebcd,edf->ebcf", xin, w_up
        )
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down)
        out = _constrain(out, P("ep", ("dp", "fsdp"), None, None))

        # Return trip (second all-to-all) + weighted combine.
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(self.dtype), out)
        return y.astype(x.dtype)
