"""Weight-only int8 quantization for serving.

The reference platform ships no inference stack at all (SURVEY.md §2.13);
this is part of the TPU rebuild's model zoo.  Rationale, TPU-first: decode
is HBM-bandwidth-bound — every generated token streams the full weight set
from HBM — so storing matmul weights as int8 (+ one scale per output
channel) halves the bytes per token versus bf16.  Dequantization happens
inside the jitted forward (``scale * int8``), which XLA fuses into the
consuming matmul: weights stay int8 in HBM and widen on the fly in
VMEM/registers, so the bandwidth saving is real, not cosmetic.

Scheme: symmetric per-channel (absmax / 127) on the LAST axis of every
``kernel``/``embedding`` leaf with rank >= 2; biases, norm scales, and
other small leaves stay in their original dtype (they are bandwidth-
irrelevant and precision-critical).

Usage::

    qparams = quantize_params(params)              # offline, once
    logits  = model.apply({"params": dequantize_params(qparams)}, tokens)
    #         ^ inside jit — the dequant fuses, HBM holds int8

``quantize_params`` returns a plain pytree (QTensor dataclass leaves), so
it checkpoints, shards (shard the ``q`` leaf exactly like the original
weight), and jits like any other params tree.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class QTensor:
    """int8 values + per-output-channel scales standing in for one weight."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        return (self.q.astype(dtype) * self.scale.astype(dtype))

    def __repr__(self):
        return f"QTensor(shape={tuple(self.q.shape)}, scale={tuple(self.scale.shape)})"


# Final path segment must be exactly `kernel` or `embedding` — a suffix
# match would also catch T5's `rel_embedding` attention-bias table, a tiny
# precision-critical leaf with zero bandwidth upside.
DEFAULT_PATTERN = re.compile(r"(^|.*\.)(kernel|embedding)$")


def _leaf_path(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
    return ".".join(parts)


def quantize_array(w: jax.Array) -> QTensor:
    """Symmetric per-channel int8: scale = absmax/127 over all but the last
    axis (output channels for the (in, ..., out) kernel convention)."""
    axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QTensor(q, scale.astype(jnp.float32))


def quantize_params(
    params: Any,
    *,
    predicate: Optional[Callable[[str, jax.Array], bool]] = None,
) -> Any:
    """Quantize every matmul weight in a params pytree to int8.

    ``predicate(path, leaf) -> bool`` overrides the default selection
    (rank >= 2 leaves whose path ends in ``kernel`` or ``embedding``).
    """

    def should(path: str, leaf) -> bool:
        if predicate is not None:
            return predicate(path, leaf)
        return (
            hasattr(leaf, "ndim") and leaf.ndim >= 2
            and DEFAULT_PATTERN.match(path) is not None
        )

    def one(path, leaf):
        name = _leaf_path(path)
        if should(name, leaf):
            return quantize_array(leaf)
        return leaf

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_params(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Widen QTensor leaves back to ``dtype`` (call INSIDE jit so XLA fuses
    the widening into each consuming matmul; HBM keeps the int8 copy)."""
    return jax.tree.map(
        lambda leaf: leaf.dequantize(dtype) if isinstance(leaf, QTensor) else leaf,
        qparams,
        is_leaf=lambda leaf: isinstance(leaf, QTensor),
    )


def quantized_bytes(params: Any) -> int:
    """Total parameter bytes (int8 + scales for QTensors, itemsize else)."""
    total = 0
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QTensor)
    ):
        if isinstance(leaf, QTensor):
            total += leaf.q.size * 1 + leaf.scale.size * 4
        elif hasattr(leaf, "size"):
            total += leaf.size * leaf.dtype.itemsize
    return total
