"""Autoregressive generation with a KV cache, jit end-to-end.

The reference platform ships no inference code (SURVEY.md §2.13); for this
stack the decode path is part of the model zoo so a spawned notebook can
serve/sample its trained models.  TPU-first mechanics:

* **Prefill** runs the whole (padded) prompt in one batched pass — MXU
  work — writing the KV cache (models/layers.py Attention._update_cache).
* **Decode** is a ``lax.scan`` over single-token steps with the cache as
  carry: static shapes, one compiled step body, no Python loop per token.
* Right-padded prompts are handled with position + cache-slot masks, so
  one compiled function serves any prompt length ≤ the bucket — no
  per-length recompiles.
* Sampling (greedy / temperature / top-k) is functional over
  ``jax.random`` keys.

Under pjit, shard the cache pytree like the activations (batch on dp, kv
heads on tp); the scan body then runs fully SPMD.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# Partition-invariant threefry: with the legacy lowering, jax.random ops
# traced with GSPMD-sharded operands generate DIFFERENT bits than the
# same ops unsharded, so seeded sampling under a serving mesh would
# diverge from the single-device stream.  The partitionable lowering
# derives every element's bits from (key, index) alone — sharded and
# unsharded sampling are bit-equal, which the sharded-vs-unsharded
# token-equality tests pin.  Set at import by every generation engine
# (parallel/sharding.py sets it for the training side).
jax.config.update("jax_threefry_partitionable", True)


def sample_logits(logits: jax.Array, rng: jax.Array, *,
                  temperature: float = 1.0,
                  top_k: Optional[int] = None) -> jax.Array:
    """Sample token ids from [batch, vocab] logits.  temperature == 0 is
    greedy; top_k restricts to the k highest-probability tokens.

    Batch-coupled (one key draws noise for the whole [batch, vocab]
    block): used by the seq2seq/beam paths.  The decoder-only generate
    path uses ``sample_logits_rows`` instead — per-row keys, so a row's
    sample stream is independent of which batch it happens to share."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # [b, 1]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def sample_logits_rows(logits: jax.Array, rngs: jax.Array, *,
                       temps: jax.Array, top_ks: jax.Array,
                       sampled: bool = True) -> jax.Array:
    """Per-row sampling over [batch, vocab] logits: row i draws with its
    OWN key ``rngs[i]`` and its own (dynamic) ``temps[i]``/``top_ks[i]``.

    This is the continuous-batching sampling contract: because no op
    couples rows, a row sampled inside the scheduler's slot pool emits
    exactly the tokens it would emit generated alone — the pool
    composition around it cannot perturb its stream.  ``temps[i] == 0``
    is greedy; ``top_ks[i] <= 0`` means unrestricted.  ``sampled=False``
    (static) compiles the pure-argmax graph — no sort/categorical work
    when every row is greedy."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not sampled:
        return greedy
    vocab = logits.shape[-1]
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    ks = jnp.clip(jnp.where(top_ks > 0, top_ks, vocab), 1, vocab)
    # kth-largest per row with a DYNAMIC k: descending sort + gather.  The
    # kth VALUE equals lax.top_k's — ties mask identically.
    sorted_desc = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
    kth = jnp.take_along_axis(sorted_desc, (ks - 1)[:, None], axis=-1)
    masked = jnp.where(scaled < kth, -1e30, scaled)
    pick = jax.vmap(jax.random.categorical)(rngs, masked).astype(jnp.int32)
    return jnp.where(temps == 0.0, greedy, pick)


def split_row_rngs(row_rngs: jax.Array):
    """Advance a [b] per-row key array one step: ``(next_rngs, subs)``
    where ``subs`` feeds this step's ``sample_logits_rows`` draw.  The
    ONE rng recipe every sampling site shares — prefill first-token,
    the sequential decode scan, and the paged engine's chunked-prefill
    sampler (models/paged.py) — so the streams stay byte-identical
    across engines by construction, not by parallel reimplementation."""
    split2 = jax.vmap(jax.random.split)(row_rngs)
    return split2[:, 0], split2[:, 1]


def _row_sampling_arrays(b: int, temperature, top_k, eos_token):
    """Scalar request knobs → per-row DYNAMIC arrays (temps, top_ks,
    eos_ids, has_eos).  Passed traced (not static) into the generate
    jits: one compiled graph serves every sampling config per shape, and
    the scheduler's slot pool can mix configs across rows of one step."""
    temps = jnp.full((b,), temperature, jnp.float32)
    top_ks = jnp.full((b,), top_k if top_k else 0, jnp.int32)
    eos_ids = jnp.full((b,), eos_token if eos_token is not None else 0,
                       jnp.int32)
    has_eos = jnp.full((b,), eos_token is not None, bool)
    return temps, top_ks, eos_ids, has_eos


def _check_cache_len(model, prompt_len: int, max_new_tokens: int) -> int:
    # The cache is bucketed to exactly the tokens this call can produce —
    # decode attends over cache_len keys, not the model's full max_seq_len
    # (an 8-token prompt + 32 new tokens on a 32k-context config would
    # otherwise pay ~800x the attention work per step).
    cache_len = prompt_len + max_new_tokens
    if cache_len > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"= {cache_len} exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    return cache_len


def _prefill_parts(model, params, prompt, prompt_mask, cache_len, *,
                   temps, top_ks, eos_ids, has_eos, sampled, rng):
    """Prefill over the padded prompt: fill the cache, sample the first
    token.  Returns ``(carry, pad_bias)`` where carry is exactly the
    decode scan's loop state ``(cache, first, lengths, row_rngs, done)``
    — shared verbatim by the one-shot ``generate`` jit, the two-phase
    ``generate_prefill``/``generate_decode`` pair, AND the continuous-
    batching scheduler (models/scheduler.py), which peels carry rows into
    its slot pool.  ``row_rngs`` is a [b] key array — ``split(rng, b)``
    — so every row owns an independent sample stream (see
    ``sample_logits_rows``)."""
    b, prompt_len = prompt.shape
    if prompt_mask is None:
        prompt_mask = jnp.ones((b, prompt_len), dtype=bool)
    prompt_mask = prompt_mask.astype(bool)
    positions = jnp.cumsum(prompt_mask.astype(jnp.int32), axis=-1) - 1
    positions = jnp.maximum(positions, 0)
    lengths = prompt_mask.sum(axis=-1).astype(jnp.int32)  # [b]

    # Padding slots hold garbage k/v after prefill (the cache is written by
    # slot, not by logical position); hide them from every later query.
    # Decode tokens land at slots >= prompt_len, which stay visible.
    slot_valid = jnp.concatenate(
        [prompt_mask,
         jnp.ones((b, cache_len - prompt_len), dtype=bool)], axis=-1
    )
    pad_bias = jnp.where(slot_valid, 0.0, -1e30)[:, None, None, :]

    # Prefill: one pass over the padded prompt fills the cache and yields
    # logits; each row samples its first token from its last valid slot.
    # token_mask keeps padding out of MoE expert routing.
    logits, state = model.apply(
        {"params": params}, prompt, positions=positions, decode=True,
        mask_bias=pad_bias, token_mask=prompt_mask, cache_len=cache_len,
        mutable=["cache"],
    )
    cache = state["cache"]
    idx = jnp.broadcast_to(
        (lengths - 1)[:, None, None], (b, 1, logits.shape[-1])
    )
    last_logits = jnp.take_along_axis(logits, idx, axis=1)[:, 0]  # [b, vocab]

    row_rngs = jax.random.split(rng, b)                   # [b] keys
    row_rngs, subs = split_row_rngs(row_rngs)
    first = sample_logits_rows(last_logits, subs, temps=temps,
                               top_ks=top_ks, sampled=sampled)
    done0 = has_eos & (first == eos_ids)
    return (cache, first, lengths, row_rngs, done0), pad_bias


def decode_step(model, params, cache, token, pos, rngs, done, bias, *,
                cache_len, temps, top_ks, eos_ids, has_eos, sampled,
                cache_slots=None):
    """ONE decode step over a [b]-row batch: apply the model on the
    current token, advance every row's key, sample per row, apply EOS
    freezing.  Returns ``(cache, next_token, pos + 1, rngs, done)``.

    This is the single compiled step body shared by the fixed-length
    ``_decode_scan`` (sequential generation; ``cache_slots=None`` — the
    flax scalar cache index advances and the model's built-in causal
    bias applies on top of ``bias``) and by the continuous-batching slot
    pool (models/scheduler.py; ``cache_slots`` is a [b] per-row write
    index and ``bias`` must carry the FULL per-row visibility mask).
    Every op is row-independent, so a row steps identically in either
    harness — the token-equality contract of continuous batching."""
    logits, state = model.apply(
        {"params": params, "cache": cache},
        token[:, None],
        positions=pos[:, None],
        decode=True,
        mask_bias=bias,
        cache_len=cache_len,
        cache_slots=cache_slots,
        mutable=["cache"],
    )
    rngs, subs = split_row_rngs(rngs)
    nxt = sample_logits_rows(logits[:, -1], subs, temps=temps,
                             top_ks=top_ks, sampled=sampled)
    nxt = jnp.where(done & has_eos, eos_ids, nxt)
    done = done | (has_eos & (nxt == eos_ids))
    return state["cache"], nxt, pos + 1, rngs, done


def _decode_scan(model, params, carry, pad_bias, *, cache_len,
                 max_new_tokens, temps, top_ks, eos_ids, has_eos, sampled):
    """The decode phase: a single ``lax.scan`` over ``decode_step`` from a
    prefilled carry.  Returns the full [batch, max_new_tokens] output
    (first token included)."""
    first = carry[1]
    if max_new_tokens == 1:
        return first[:, None]

    def step(carry, _):
        cache, token, pos, rngs, done = carry
        cache, nxt, pos, rngs, done = decode_step(
            model, params, cache, token, pos, rngs, done, pad_bias,
            cache_len=cache_len, temps=temps, top_ks=top_ks,
            eos_ids=eos_ids, has_eos=has_eos, sampled=sampled,
        )
        return (cache, nxt, pos, rngs, done), nxt

    _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "sampled"),
)
def _generate_jit(model, params, prompt, *, rng, prompt_mask, temps,
                  top_ks, eos_ids, has_eos, max_new_tokens, sampled):
    # int8-served params widen here, INSIDE the jit, so XLA fuses the
    # dequant into each consuming matmul and HBM keeps the int8 copy
    # (models/quantize.py); plain params pass through untouched.
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    cache_len = _check_cache_len(model, prompt.shape[1], max_new_tokens)
    carry, pad_bias = _prefill_parts(
        model, params, prompt, prompt_mask, cache_len,
        temps=temps, top_ks=top_ks, eos_ids=eos_ids, has_eos=has_eos,
        sampled=sampled, rng=rng,
    )
    return _decode_scan(
        model, params, carry, pad_bias, cache_len=cache_len,
        max_new_tokens=max_new_tokens, temps=temps, top_ks=top_ks,
        eos_ids=eos_ids, has_eos=has_eos, sampled=sampled,
    )


def generate(model, params, prompt: jax.Array, *,
             rng: Optional[jax.Array] = None,
             prompt_mask: Optional[jax.Array] = None,
             max_new_tokens: int = 32,
             temperature: float = 0.0,
             top_k: Optional[int] = None,
             eos_token: Optional[int] = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations for a [batch, prompt_len]
    right-padded prompt (``prompt_mask`` True on real tokens).  Returns
    [batch, max_new_tokens] token ids; after an EOS the row pads with EOS.

    ``model`` must be a Llama-style module whose ``__call__`` supports
    ``decode=True`` with a "cache" collection; its ``max_seq_len`` must
    bound prompt_len + max_new_tokens.

    Sampling is per-row (``sample_logits_rows``): row i draws from key
    ``split(rng, b)[i]``, so a row's stream depends only on its own key —
    never on which rows share the batch.  temperature/top_k/eos ride as
    DYNAMIC arrays, so one compiled graph per shape serves every
    sampling config.

    MoE caveat: capacity-truncated routing is sequence-length dependent by
    construction (per-step decode has fresh capacity; a full re-forward
    shares capacity across the whole sequence), so for ``n_experts > 0``
    cached decode equals the re-forward oracle only while no token is
    dropped — the standard Switch/GShard decode behavior.
    """
    if rng is None:
        rng = jax.random.key(0)
    temps, top_ks, eos_ids, has_eos = _row_sampling_arrays(
        prompt.shape[0], temperature, top_k, eos_token)
    return _generate_jit(
        model, params, prompt, rng=rng, prompt_mask=prompt_mask,
        temps=temps, top_ks=top_ks, eos_ids=eos_ids, has_eos=has_eos,
        max_new_tokens=max_new_tokens, sampled=temperature != 0.0,
    )


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "sampled"),
)
def _generate_prefill_jit(model, params, prompt, *, rng, prompt_mask,
                          temps, top_ks, eos_ids, has_eos,
                          max_new_tokens, sampled):
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    cache_len = _check_cache_len(model, prompt.shape[1], max_new_tokens)
    carry, pad_bias = _prefill_parts(
        model, params, prompt, prompt_mask, cache_len,
        temps=temps, top_ks=top_ks, eos_ids=eos_ids, has_eos=has_eos,
        sampled=sampled, rng=rng,
    )
    return carry[1], (carry, pad_bias)


def generate_prefill(model, params, prompt: jax.Array, *,
                     rng: Optional[jax.Array] = None,
                     prompt_mask: Optional[jax.Array] = None,
                     max_new_tokens: int = 32,
                     temperature: float = 0.0,
                     top_k: Optional[int] = None,
                     eos_token: Optional[int] = None):
    """Phase 1 of two-phase generation: the prompt pass alone.  Returns
    ``(first_token [batch], decode_state)``; hand decode_state to
    ``generate_decode`` for the rest.

    Runs EXACTLY the ops of ``generate``'s prefill half (shared
    ``_prefill_parts``), just jitted at a phase boundary — the seam serve
    telemetry measures time-to-first-token at, and the seam the
    continuous-batching scheduler (models/scheduler.py) admits requests
    into: decode_state's carry rows peel apart into pool slots.  The
    token budget rides along in decode_state (a host-side int, outside
    the jit): the cache was sized for THIS budget, so decode must not
    run with any other."""
    if rng is None:
        rng = jax.random.key(0)
    temps, top_ks, eos_ids, has_eos = _row_sampling_arrays(
        prompt.shape[0], temperature, top_k, eos_token)
    first, state = _generate_prefill_jit(
        model, params, prompt, rng=rng, prompt_mask=prompt_mask,
        temps=temps, top_ks=top_ks, eos_ids=eos_ids, has_eos=has_eos,
        max_new_tokens=max_new_tokens, sampled=temperature != 0.0,
    )
    return first, (state, max_new_tokens)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "sampled"),
    # Donate the prefilled KV cache: without this the decode scan's
    # working cache would coexist with the (dead) prefill output and the
    # two-phase path would hold ~2x the one-shot jit's cache HBM at peak.
    donate_argnums=(2,),
)
def _generate_decode_jit(model, params, state, *, temps, top_ks, eos_ids,
                         has_eos, max_new_tokens, sampled):
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    carry, pad_bias = state
    cache_len = pad_bias.shape[-1]
    return _decode_scan(
        model, params, carry, pad_bias, cache_len=cache_len,
        max_new_tokens=max_new_tokens, temps=temps, top_ks=top_ks,
        eos_ids=eos_ids, has_eos=has_eos, sampled=sampled,
    )


def generate_decode(model, params, decode_state, *,
                    max_new_tokens: Optional[int] = None,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    eos_token: Optional[int] = None) -> jax.Array:
    """Phase 2 of two-phase generation: the decode scan from a
    ``generate_prefill`` state.  Returns the full
    [batch, max_new_tokens] output (first token included), matching
    ``generate``'s contract.

    ``max_new_tokens`` defaults to the budget the prefill sized the
    cache for; passing a DIFFERENT value raises — a longer scan would
    silently write past cache_len (clamped into the last slot) and
    return garbage continuations, never an error."""
    state, prefill_budget = decode_state
    if max_new_tokens is None:
        max_new_tokens = prefill_budget
    elif max_new_tokens != prefill_budget:
        raise ValueError(
            f"max_new_tokens {max_new_tokens} does not match the budget "
            f"the prefill sized its cache for ({prefill_budget})"
        )
    b = state[0][1].shape[0]
    temps, top_ks, eos_ids, has_eos = _row_sampling_arrays(
        b, temperature, top_k, eos_token)
    return _generate_decode_jit(
        model, params, state, temps=temps, top_ks=top_ks, eos_ids=eos_ids,
        has_eos=has_eos, max_new_tokens=max_new_tokens,
        sampled=temperature != 0.0,
    )


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "bos_token", "eos_token"),
)
def generate_seq2seq(model, params, source: jax.Array, *,
                     source_mask: Optional[jax.Array] = None,
                     rng: Optional[jax.Array] = None,
                     max_new_tokens: int = 32,
                     bos_token: int = 0,
                     eos_token: Optional[int] = 1,
                     temperature: float = 0.0,
                     top_k: Optional[int] = None) -> jax.Array:
    """Encoder-decoder generation (T5-style model with ``encode`` /
    ``decode`` apply methods): encode the source ONCE, then scan cached
    single-token decoder steps.  Returns [batch, max_new_tokens] token ids;
    rows pad with EOS after emitting it.

    T5 convention: decoding starts from ``bos_token`` (the pad id, 0) and
    ``eos_token`` is 1.
    """
    # int8-served params widen inside the jit (see generate()).
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b = source.shape[0]
    if rng is None:
        rng = jax.random.key(0)
    if source_mask is not None:
        source_mask = source_mask.astype(bool)
    encoded = model.apply({"params": params}, source, source_mask,
                          method="encode")
    # Cache sizes to exactly the decode budget: step t attends slots <= t.
    cache_len = max_new_tokens
    tok0 = jnp.full((b, 1), bos_token, jnp.int32)
    logits, state = model.apply(
        {"params": params}, encoded, tok0,
        source_mask=source_mask, decode=True,
        step=jnp.zeros((), jnp.int32), max_decode_len=cache_len,
        mutable=["cache"], method="decode",
    )
    rng, sub = jax.random.split(rng)
    first = sample_logits(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k)

    def step_fn(carry, i):
        cache, token, rng, done = carry
        rng, sub = jax.random.split(rng)
        logits, new_state = model.apply(
            {"params": params, "cache": cache}, encoded, token[:, None],
            source_mask=source_mask, decode=True,
            step=i, max_decode_len=cache_len,
            mutable=["cache"], method="decode",
        )
        nxt = sample_logits(logits[:, -1], sub, temperature=temperature,
                            top_k=top_k)
        if eos_token is not None:
            nxt = jnp.where(done, eos_token, nxt)
            done = done | (nxt == eos_token)
        return (new_state["cache"], nxt, rng, done), nxt

    done0 = jnp.zeros((b,), dtype=bool)
    if eos_token is not None:
        done0 = first == eos_token
    if max_new_tokens == 1:
        return first[:, None]
    carry = (state["cache"], first, rng, done0)
    _, rest = jax.lax.scan(
        step_fn, carry, jnp.arange(1, max_new_tokens, dtype=jnp.int32)
    )
    return jnp.concatenate([first[:, None], rest.T], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "beams", "bos_token",
                     "eos_token", "length_penalty"),
)
def beam_search_seq2seq(model, params, source: jax.Array, *,
                        source_mask: Optional[jax.Array] = None,
                        max_new_tokens: int = 32,
                        beams: int = 4,
                        bos_token: int = 0,
                        eos_token: int = 1,
                        length_penalty: float = 0.6) -> jax.Array:
    """Beam search for encoder-decoder models, jit end-to-end.

    The beam axis folds into the batch axis (``b*beams`` rows share one
    cached decoder), each step expands every live beam over the vocab and
    keeps the ``beams`` best by score; the KV cache rows are re-gathered
    to follow their parent beam (one ``take`` per step — the scan stays a
    single compiled program).  Finished beams (emitted EOS) freeze: they
    only continue with EOS at zero added score.  Final ranking uses GNMT
    length normalization ``score / ((5+len)/6)^length_penalty``.

    Returns [batch, max_new_tokens] token ids of the best beam.
    """
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b, src_len = source.shape
    if source_mask is None:
        source_mask = jnp.ones((b, src_len), dtype=bool)
    source_mask = source_mask.astype(bool)

    # Encode once, then tile to the beam-folded batch.
    encoded = model.apply({"params": params}, source, source_mask,
                          method="encode")
    encoded = jnp.repeat(encoded, beams, axis=0)          # [b*beams, S, d]
    mask_r = jnp.repeat(source_mask, beams, axis=0)
    cache_len = max_new_tokens

    tok0 = jnp.full((b * beams, 1), bos_token, jnp.int32)
    logits, state = model.apply(
        {"params": params}, encoded, tok0,
        source_mask=mask_r, decode=True,
        step=jnp.zeros((), jnp.int32), max_decode_len=cache_len,
        mutable=["cache"], method="decode",
    )
    logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
    vocab = logp0.shape[-1]
    logp0 = logp0.reshape(b, beams, vocab)[:, 0]          # beams identical

    def step_apply(cache, token, i):
        logits, new_state = model.apply(
            {"params": params, "cache": cache}, encoded,
            token.reshape(b * beams, 1),
            source_mask=mask_r, decode=True,
            step=i, max_decode_len=cache_len,
            mutable=["cache"], method="decode",
        )
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1
        ).reshape(b, beams, vocab)
        return logp, new_state["cache"]

    return _beam_loop(
        step_apply, logp0, state["cache"], b=b, beams=beams, vocab=vocab,
        eos_token=eos_token, length_penalty=length_penalty,
        max_new_tokens=max_new_tokens,
    )


def _beam_loop(step_apply, logp0, cache0, *, b, beams, vocab, eos_token,
               length_penalty, max_new_tokens):
    """Shared beam machinery: seed from ``logp0`` [b, vocab], scan
    ``step_apply(cache, token [b, beams], i) -> (logp [b, beams, vocab],
    cache)`` steps with parent re-gather and EOS freezing, backtrack, and
    rank with GNMT length normalization.  ``cache0`` must already be
    beam-tiled ([b*beams] leading rows).  ``eos_token=None`` disables
    freezing (pure max-score search)."""
    scores, first = jax.lax.top_k(logp0, beams)           # [b, beams]
    first = first.astype(jnp.int32)
    alive = (
        first != eos_token if eos_token is not None
        else jnp.ones((b, beams), dtype=bool)
    )

    def step_fn(carry, i):
        cache, token, scores, alive = carry
        logp, cache = step_apply(cache, token, i)
        if eos_token is not None:
            # Frozen beams may only emit EOS, at no score change.
            eos_only = jnp.full((vocab,), -jnp.inf).at[eos_token].set(0.0)
            logp = jnp.where(alive[..., None], logp, eos_only[None, None])
        total = scores[..., None] + logp                  # [b, beams, V]
        flat_scores, flat_idx = jax.lax.top_k(
            total.reshape(b, beams * vocab), beams
        )
        parent = (flat_idx // vocab).astype(jnp.int32)    # [b, beams]
        token = (flat_idx % vocab).astype(jnp.int32)
        # Re-gather cache rows to follow the surviving beams' parents.
        # Cross-attention K/V are identical across a batch group's beams
        # (projected from the repeated encoder output), so gathering them
        # would be a semantic no-op costing a full HBM copy per step —
        # skip them.
        gather = (jnp.arange(b)[:, None] * beams + parent).reshape(-1)

        def regather(path, x):
            if any("cached_cross" in str(getattr(p, "key", "")) for p in path):
                return x
            if hasattr(x, "ndim") and x.ndim > 0 and x.shape[0] == b * beams:
                return jnp.take(x, gather, axis=0)
            return x

        cache = jax.tree_util.tree_map_with_path(regather, cache)
        if eos_token is not None:
            alive = jnp.take_along_axis(alive, parent, axis=1) & (
                token != eos_token
            )
        return (cache, token, flat_scores, alive), (token, parent)

    carry = (cache0, first, scores, alive)
    if max_new_tokens == 1:
        return first[:, :1]
    (cache, token, scores, alive), (toks, parents) = jax.lax.scan(
        step_fn, carry, jnp.arange(1, max_new_tokens, dtype=jnp.int32)
    )

    # Backtrack the parent pointers into full sequences [b, beams, T].
    def back(carry, tp):
        beam_idx = carry
        tok_t, parent_t = tp
        tok = jnp.take_along_axis(tok_t, beam_idx, axis=1)
        beam_idx = jnp.take_along_axis(parent_t, beam_idx, axis=1)
        return beam_idx, tok

    beam_idx0 = jnp.broadcast_to(jnp.arange(beams)[None], (b, beams))
    beam_idx, rev = jax.lax.scan(
        back, beam_idx0, (toks, parents), reverse=True
    )
    first_tok = jnp.take_along_axis(first, beam_idx, axis=1)
    seqs = jnp.concatenate(
        [first_tok[:, :, None], jnp.moveaxis(rev, 0, 2)], axis=2
    )                                                     # [b, beams, T]
    # GNMT length normalization over the effective length: tokens up to
    # and including the first EOS, capped at T for beams that never
    # finished (the uncapped sum+1 would credit them a phantom token and
    # skew the normalized ranking toward unfinished beams).
    if eos_token is not None:
        lengths = jnp.minimum(
            jnp.sum(jnp.cumprod(seqs != eos_token, axis=2), axis=2) + 1.0,
            float(seqs.shape[2]),
        )
    else:
        lengths = jnp.full((b, beams), float(seqs.shape[2]))
    norm = ((5.0 + lengths) / 6.0) ** length_penalty
    best = jnp.argmax(scores / norm, axis=1)              # [b]
    return jnp.take_along_axis(
        seqs, best[:, None, None], axis=1
    )[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "beams", "eos_token",
                     "length_penalty"),
)
def beam_search(model, params, prompt: jax.Array, *,
                prompt_mask: Optional[jax.Array] = None,
                max_new_tokens: int = 32,
                beams: int = 4,
                eos_token: Optional[int] = None,
                length_penalty: float = 0.6) -> jax.Array:
    """Beam search for decoder-only models: one prefill over the prompt,
    then the shared beam loop (cache tiled to b*beams rows, parent
    re-gather per step).  Same prompt-padding contract as ``generate``.

    Returns [batch, max_new_tokens] token ids of the best beam."""
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b, prompt_len = prompt.shape
    cache_len = prompt_len + max_new_tokens
    if cache_len > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"= {cache_len} exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    if prompt_mask is None:
        prompt_mask = jnp.ones((b, prompt_len), dtype=bool)
    prompt_mask = prompt_mask.astype(bool)
    positions = jnp.maximum(
        jnp.cumsum(prompt_mask.astype(jnp.int32), axis=-1) - 1, 0
    )
    lengths = prompt_mask.sum(axis=-1).astype(jnp.int32)  # [b]
    slot_valid = jnp.concatenate(
        [prompt_mask,
         jnp.ones((b, cache_len - prompt_len), dtype=bool)], axis=-1
    )
    pad_bias = jnp.where(slot_valid, 0.0, -1e30)[:, None, None, :]

    # Prefill on the raw batch, then tile cache/bias/positions per beam.
    logits, state = model.apply(
        {"params": params}, prompt, positions=positions, decode=True,
        mask_bias=pad_bias, token_mask=prompt_mask, cache_len=cache_len,
        mutable=["cache"],
    )
    idx = jnp.broadcast_to(
        (lengths - 1)[:, None, None], (b, 1, logits.shape[-1])
    )
    logp0 = jax.nn.log_softmax(
        jnp.take_along_axis(logits, idx, axis=1)[:, 0].astype(jnp.float32),
        axis=-1,
    )                                                     # [b, vocab]
    vocab = logp0.shape[-1]
    cache0 = jax.tree.map(
        lambda x: jnp.repeat(x, beams, axis=0)
        if hasattr(x, "ndim") and x.ndim > 0 and x.shape[0] == b else x,
        state["cache"],
    )
    pad_bias_r = jnp.repeat(pad_bias, beams, axis=0)
    lengths_r = jnp.repeat(lengths, beams, axis=0)        # [b*beams]

    def step_apply(cache, token, i):
        # Scan step i feeds generated token i-1, at position lengths+i-1.
        pos = (lengths_r + i - 1)[:, None]
        logits, new_state = model.apply(
            {"params": params, "cache": cache},
            token.reshape(b * beams, 1),
            positions=pos, decode=True, mask_bias=pad_bias_r,
            cache_len=cache_len, mutable=["cache"],
        )
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1
        ).reshape(b, beams, vocab)
        return logp, new_state["cache"]

    return _beam_loop(
        step_apply, logp0, cache0, b=b, beams=beams, vocab=vocab,
        eos_token=eos_token, length_penalty=length_penalty,
        max_new_tokens=max_new_tokens,
    )
