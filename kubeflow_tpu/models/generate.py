"""Autoregressive generation with a KV cache, jit end-to-end.

The reference platform ships no inference code (SURVEY.md §2.13); for this
stack the decode path is part of the model zoo so a spawned notebook can
serve/sample its trained models.  TPU-first mechanics:

* **Prefill** runs the whole (padded) prompt in one batched pass — MXU
  work — writing the KV cache (models/layers.py Attention._update_cache).
* **Decode** is a ``lax.scan`` over single-token steps with the cache as
  carry: static shapes, one compiled step body, no Python loop per token.
* Right-padded prompts are handled with position + cache-slot masks, so
  one compiled function serves any prompt length ≤ the bucket — no
  per-length recompiles.
* Sampling (greedy / temperature / top-k) is functional over
  ``jax.random`` keys.

Under pjit, shard the cache pytree like the activations (batch on dp, kv
heads on tp); the scan body then runs fully SPMD.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def sample_logits(logits: jax.Array, rng: jax.Array, *,
                  temperature: float = 1.0,
                  top_k: Optional[int] = None) -> jax.Array:
    """Sample token ids from [batch, vocab] logits.  temperature == 0 is
    greedy; top_k restricts to the k highest-probability tokens."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]  # [b, 1]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "eos_token"),
)
def generate(model, params, prompt: jax.Array, *,
             rng: Optional[jax.Array] = None,
             prompt_mask: Optional[jax.Array] = None,
             max_new_tokens: int = 32,
             temperature: float = 0.0,
             top_k: Optional[int] = None,
             eos_token: Optional[int] = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations for a [batch, prompt_len]
    right-padded prompt (``prompt_mask`` True on real tokens).  Returns
    [batch, max_new_tokens] token ids; after an EOS the row pads with EOS.

    ``model`` must be a Llama-style module whose ``__call__`` supports
    ``decode=True`` with a "cache" collection; its ``max_seq_len`` must
    bound prompt_len + max_new_tokens.

    MoE caveat: capacity-truncated routing is sequence-length dependent by
    construction (per-step decode has fresh capacity; a full re-forward
    shares capacity across the whole sequence), so for ``n_experts > 0``
    cached decode equals the re-forward oracle only while no token is
    dropped — the standard Switch/GShard decode behavior.
    """
    # int8-served params widen here, INSIDE the jit, so XLA fuses the
    # dequant into each consuming matmul and HBM keeps the int8 copy
    # (models/quantize.py); plain params pass through untouched.
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b, prompt_len = prompt.shape
    # The cache is bucketed to exactly the tokens this call can produce —
    # decode attends over cache_len keys, not the model's full max_seq_len
    # (an 8-token prompt + 32 new tokens on a 32k-context config would
    # otherwise pay ~800x the attention work per step).
    cache_len = prompt_len + max_new_tokens
    if cache_len > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"= {cache_len} exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    if rng is None:
        rng = jax.random.key(0)
    if prompt_mask is None:
        prompt_mask = jnp.ones((b, prompt_len), dtype=bool)
    prompt_mask = prompt_mask.astype(bool)
    positions = jnp.cumsum(prompt_mask.astype(jnp.int32), axis=-1) - 1
    positions = jnp.maximum(positions, 0)
    lengths = prompt_mask.sum(axis=-1).astype(jnp.int32)  # [b]

    # Padding slots hold garbage k/v after prefill (the cache is written by
    # slot, not by logical position); hide them from every later query.
    # Decode tokens land at slots >= prompt_len, which stay visible.
    slot_valid = jnp.concatenate(
        [prompt_mask,
         jnp.ones((b, cache_len - prompt_len), dtype=bool)], axis=-1
    )
    pad_bias = jnp.where(slot_valid, 0.0, -1e30)[:, None, None, :]

    # Prefill: one pass over the padded prompt fills the cache and yields
    # logits; each row samples its first token from its last valid slot.
    # token_mask keeps padding out of MoE expert routing.
    logits, state = model.apply(
        {"params": params}, prompt, positions=positions, decode=True,
        mask_bias=pad_bias, token_mask=prompt_mask, cache_len=cache_len,
        mutable=["cache"],
    )
    cache = state["cache"]
    idx = jnp.broadcast_to(
        (lengths - 1)[:, None, None], (b, 1, logits.shape[-1])
    )
    last_logits = jnp.take_along_axis(logits, idx, axis=1)[:, 0]  # [b, vocab]

    rng, sub = jax.random.split(rng)
    first = sample_logits(last_logits, sub, temperature=temperature,
                          top_k=top_k)

    def step(carry, _):
        cache, token, pos, rng, done = carry
        rng, sub = jax.random.split(rng)
        logits, state = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            positions=pos[:, None],
            decode=True,
            mask_bias=pad_bias,
            cache_len=cache_len,
            mutable=["cache"],
        )
        nxt = sample_logits(logits[:, -1], sub, temperature=temperature,
                            top_k=top_k)
        if eos_token is not None:
            nxt = jnp.where(done, eos_token, nxt)
            done = done | (nxt == eos_token)
        return (state["cache"], nxt, pos + 1, rng, done), nxt

    done0 = jnp.zeros((b,), dtype=bool)
    if eos_token is not None:
        done0 = first == eos_token
    if max_new_tokens == 1:
        return first[:, None]
    carry = (cache, first, lengths, rng, done0)
    _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "temperature", "top_k",
                     "bos_token", "eos_token"),
)
def generate_seq2seq(model, params, source: jax.Array, *,
                     source_mask: Optional[jax.Array] = None,
                     rng: Optional[jax.Array] = None,
                     max_new_tokens: int = 32,
                     bos_token: int = 0,
                     eos_token: Optional[int] = 1,
                     temperature: float = 0.0,
                     top_k: Optional[int] = None) -> jax.Array:
    """Encoder-decoder generation (T5-style model with ``encode`` /
    ``decode`` apply methods): encode the source ONCE, then scan cached
    single-token decoder steps.  Returns [batch, max_new_tokens] token ids;
    rows pad with EOS after emitting it.

    T5 convention: decoding starts from ``bos_token`` (the pad id, 0) and
    ``eos_token`` is 1.
    """
    # int8-served params widen inside the jit (see generate()).
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b = source.shape[0]
    if rng is None:
        rng = jax.random.key(0)
    if source_mask is not None:
        source_mask = source_mask.astype(bool)
    encoded = model.apply({"params": params}, source, source_mask,
                          method="encode")
    # Cache sizes to exactly the decode budget: step t attends slots <= t.
    cache_len = max_new_tokens
    tok0 = jnp.full((b, 1), bos_token, jnp.int32)
    logits, state = model.apply(
        {"params": params}, encoded, tok0,
        source_mask=source_mask, decode=True,
        step=jnp.zeros((), jnp.int32), max_decode_len=cache_len,
        mutable=["cache"], method="decode",
    )
    rng, sub = jax.random.split(rng)
    first = sample_logits(logits[:, -1], sub, temperature=temperature,
                          top_k=top_k)

    def step_fn(carry, i):
        cache, token, rng, done = carry
        rng, sub = jax.random.split(rng)
        logits, new_state = model.apply(
            {"params": params, "cache": cache}, encoded, token[:, None],
            source_mask=source_mask, decode=True,
            step=i, max_decode_len=cache_len,
            mutable=["cache"], method="decode",
        )
        nxt = sample_logits(logits[:, -1], sub, temperature=temperature,
                            top_k=top_k)
        if eos_token is not None:
            nxt = jnp.where(done, eos_token, nxt)
            done = done | (nxt == eos_token)
        return (new_state["cache"], nxt, rng, done), nxt

    done0 = jnp.zeros((b,), dtype=bool)
    if eos_token is not None:
        done0 = first == eos_token
    if max_new_tokens == 1:
        return first[:, None]
    carry = (state["cache"], first, rng, done0)
    _, rest = jax.lax.scan(
        step_fn, carry, jnp.arange(1, max_new_tokens, dtype=jnp.int32)
    )
    return jnp.concatenate([first[:, None], rest.T], axis=1)
