"""Wire client for the /v1/generate contract (and the activator path).

The serving front door (platform/activator.py) speaks plain HTTP with a
small QoS vocabulary in headers — tenant, priority class, deadline — and
structured failure envelopes with Retry-After on every backpressure
outcome (429 bucket/shed, 503 hold-overflow/wake-timeout/warming, 504
deadline).  This module is the ONE client-side reading of that contract:
the activator's replay loop, the conformance harnesses, and the bench
all build requests and parse outcomes through it, so a wire change shows
up as exactly one diff.

Deliberately stdlib-only (urllib, json): importing it must never pull
jax — the activator and the controllers are jax-free processes.
"""
from __future__ import annotations

import json
import random
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

HEADER_TENANT = "X-KFT-Tenant"
HEADER_PRIORITY = "X-KFT-Priority"
HEADER_DEADLINE = "X-KFT-Deadline-Seconds"


def full_jitter_backoff(attempt: int, *, base: float, cap: float,
                        rng: Optional[random.Random] = None) -> float:
    """Full-jitter exponential backoff (the AWS architecture-blog
    variant): uniform in [0, min(cap, base * 2^attempt)].  Full jitter —
    rather than equal or decorrelated — because the activator replays a
    whole hold queue at once when a service wakes; synchronized retries
    from N held requests would thundering-herd the one replica that just
    warmed."""
    rng = rng if rng is not None else random
    return rng.uniform(0.0, min(cap, base * (2.0 ** max(attempt, 0))))


@dataclass
class GenerateResult:
    """One wire outcome.  ``ok`` iff HTTP 200; otherwise ``status``/
    ``log`` carry the structured failure envelope and ``retry_after``
    the server's Retry-After seconds when it sent one (429/503)."""

    status: int
    tokens: Optional[List[List[int]]] = None
    log: str = ""
    retry_after: Optional[float] = None
    headers: Dict[str, str] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return self.status == 200

    @property
    def retryable(self) -> bool:
        """Whether a replay can possibly succeed: backpressure outcomes
        (429/503) are retryable after Retry-After; 504 means the request
        itself is dead (deadline) — replaying it replays a corpse."""
        return self.status in (429, 503)


class GenerateClient:
    """Thin /v1/generate caller with the QoS headers attached.

    ``base_url`` is either a replica root (``http://host:port``) or an
    activator service prefix (``http://front:port/serve/<ns>/<name>``) —
    the path shape is identical past the prefix, which is the whole
    point of the VirtualService rewrite."""

    def __init__(self, base_url: str, *, tenant: Optional[str] = None,
                 priority: Optional[str] = None,
                 timeout: float = 30.0, opener=None):
        self.base_url = base_url.rstrip("/")
        self.tenant = tenant
        self.priority = priority
        self.timeout = timeout
        # Hook for hermetic tests: opener(request, timeout) -> response
        # object with .status/.headers/.read().
        self._opener = opener or (
            lambda req, timeout: urllib.request.urlopen(req, timeout=timeout))

    def headers(self, *, deadline_seconds: Optional[float] = None,
                traceparent: Optional[str] = None) -> Dict[str, str]:
        out = {"Content-Type": "application/json"}
        if self.tenant:
            out[HEADER_TENANT] = self.tenant
        if self.priority:
            out[HEADER_PRIORITY] = self.priority
        if deadline_seconds is not None:
            out[HEADER_DEADLINE] = f"{deadline_seconds:.3f}"
        if traceparent:
            out["traceparent"] = traceparent
        return out

    def generate(self, tokens: List[List[int]], *,
                 max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, seed: int = 0,
                 deadline_seconds: Optional[float] = None,
                 traceparent: Optional[str] = None) -> GenerateResult:
        body: dict = {"tokens": tokens, "temperature": temperature,
                      "seed": seed}
        if max_new_tokens is not None:
            body["max_new_tokens"] = max_new_tokens
        req = urllib.request.Request(
            self.base_url + "/v1/generate",
            data=json.dumps(body).encode(),
            headers=self.headers(deadline_seconds=deadline_seconds,
                                 traceparent=traceparent),
            method="POST")
        try:
            with self._opener(req, self.timeout) as resp:
                return _parse(resp.status, dict(resp.headers), resp.read())
        except urllib.error.HTTPError as e:
            return _parse(e.code, dict(e.headers or {}), e.read())
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            # Transport failure: surface as a retryable 503-shaped
            # outcome so callers' retry loops need one code path.
            return GenerateResult(status=503, log=f"transport: {e}")


def _parse(status: int, headers: Dict[str, str], raw: bytes
           ) -> GenerateResult:
    headers = {k.lower(): v for k, v in headers.items()}
    retry_after = None
    if headers.get("retry-after"):
        try:
            retry_after = float(headers["retry-after"])
        except ValueError:
            retry_after = None
    try:
        body = json.loads(raw.decode("utf-8", "replace")) or {}
    except ValueError:
        body = {}
    return GenerateResult(
        status=status,
        tokens=body.get("tokens") if status == 200 else None,
        log=str(body.get("log", "")),
        retry_after=retry_after,
        headers=headers,
    )
