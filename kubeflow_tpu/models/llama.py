"""Llama-family decoder in Flax — the multi-host pjit flagship.

BASELINE.json config 5 is "multi-host TPU slice notebook (v5e-16, JAX pjit
Llama-2-7B)"; the reference platform only *schedules* such a notebook and
ships no model (SURVEY.md §2.13).  Here the model itself is part of the
stack, designed for SPMD from the start:

* Pure functional forward; all sharding is applied externally by
  ``kubeflow_tpu.parallel.sharding`` rules over the param pytree paths — the
  model stays mesh-agnostic.
* GQA + RoPE + RMSNorm + SwiGLU (Llama-2/3 shape); attention runs through
  the Pallas flash kernel at long sequence.
* Optional ``remat`` per layer (jax.checkpoint) to trade FLOPs for HBM.
* Static shapes everywhere; the layer stack is a Python loop over identical
  blocks, which XLA deduplicates into one compiled body per unique shape.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.layers import Attention, Embed, RMSNorm, SwiGLU
from kubeflow_tpu.models.registry import register_model


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # With remat=True: "block" recomputes the whole layer in backward (the
    # classic memory-min setting); "mlp" recomputes only the FFN — the
    # hidden [b, s, ffn_dim] pair is the dominant activation — while the
    # attention residuals stay saved, so the flash kernel's forward never
    # re-runs in backward.  Measured on the llama-8k bench config: "mlp"
    # recovers most of the no-remat throughput at a fraction of its
    # memory (BASELINE.md round 3).
    remat_mode: str = "block"  # "block" | "mlp"
    attn_impl: str = "auto"
    # Stack the identical blocks into one lax.scan (nn.scan): one compiled
    # block body instead of n_layers inlined copies — compile time drops
    # near-linearly with depth, the standard TPU idiom for 32+ layer models.
    # Params gain a leading layer axis; parallel.sharding prepends None to
    # the matched spec for paths under "layers_scan".
    scan_layers: bool = False
    # MoE (Mixtral-style): n_experts == 0 means a dense SwiGLU MLP.
    n_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25

    def __post_init__(self):
        if self.remat_mode not in ("block", "mlp"):
            # All remat sites gate on exact equality; an unknown value
            # would silently disable remat and blow the memory budget.
            raise ValueError(
                f"remat_mode must be 'block' or 'mlp', got "
                f"{self.remat_mode!r}"
            )

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


# Published Llama-2/3 shapes plus tiny/test scales.
CONFIGS = {
    "llama_debug": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=256, dtype=jnp.float32,
    ),
    "llama_125m": LlamaConfig(
        vocab_size=32000, dim=768, n_layers=12, n_heads=12, n_kv_heads=12,
        ffn_dim=2048,
    ),
    # ~1.36B params: the single-16GB-chip scale where weight-only int8
    # serving can actually pay (BASELINE.md int8 A/B) — 125M decode is
    # latency-bound, 7B doesn't fit a bf16 A/B arm.
    "llama_1b4": LlamaConfig(
        vocab_size=32000, dim=2048, n_layers=24, n_heads=16, n_kv_heads=16,
        ffn_dim=5632,
    ),
    "llama2_7b": LlamaConfig(),
    "llama2_13b": LlamaConfig(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                              ffn_dim=13824),
    "llama3_8b": LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, ffn_dim=14336,
                             rope_theta=500000.0, max_seq_len=8192),
    # Mixtral-style sparse MoE decoders (expert-parallel over the ep axis).
    "mixtral_debug": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=256, dtype=jnp.float32, n_experts=4,
    ),
    "mixtral_8x7b": LlamaConfig(
        dim=4096, n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14336,
        max_seq_len=32768, rope_theta=1000000.0, n_experts=8, top_k=2,
    ),
}


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids, decode=False,
                 mask_bias=None, token_mask=None, cache_len=None,
                 cache_slots=None):
        cfg = self.cfg
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="attn_norm")(x)
        h = Attention(
            num_heads=cfg.n_heads,
            num_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim,
            rope=True,
            rope_theta=cfg.rope_theta,
            causal=True,
            dtype=cfg.dtype,
            attn_impl=cfg.attn_impl,
            name="attn",
        )(h, positions=positions, segment_ids=segment_ids, decode=decode,
          max_decode_len=cache_len or cfg.max_seq_len, mask_bias=mask_bias,
          cache_slots=cache_slots)
        x = x + h
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="mlp_norm")(x)
        # remat_mode="mlp": recompute only the FFN hiddens in backward (the
        # wrapped class keeps the "mlp" param path, so sharding rules and
        # checkpoints are unchanged).
        ffn_remat = cfg.remat and cfg.remat_mode == "mlp"
        if cfg.n_experts > 0:
            from kubeflow_tpu.models.moe import MoeMlp

            moe_cls = nn.remat(MoeMlp) if ffn_remat else MoeMlp
            h = moe_cls(
                n_experts=cfg.n_experts,
                hidden_dim=cfg.ffn_dim,
                top_k=cfg.top_k,
                capacity_factor=cfg.capacity_factor,
                dtype=cfg.dtype,
                name="mlp",
            )(h, token_mask=token_mask)
        else:
            swiglu_cls = nn.remat(SwiGLU) if ffn_remat else SwiGLU
            h = swiglu_cls(hidden_dim=cfg.ffn_dim, dtype=cfg.dtype,
                           name="mlp")(h)
        return x + h


class LlamaScanBody(nn.Module):
    """nn.scan body: carry = activations, no per-layer outputs."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids, decode, mask_bias,
                 token_mask, cache_len, cache_slots):
        block = LlamaBlock
        if self.cfg.remat and self.cfg.remat_mode == "block":
            block = nn.remat(LlamaBlock, static_argnums=(4, 7))
        x = block(self.cfg, name="block")(
            x, positions, segment_ids, decode, mask_bias, token_mask,
            cache_len, cache_slots,
        )
        return x, None


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, *, positions=None, segment_ids=None,
                 decode=False, mask_bias=None, token_mask=None,
                 cache_len=None, cache_slots=None, return_hidden=False):
        cfg = self.cfg
        b, s = tokens.shape
        if cache_len is not None and cache_len > cfg.max_seq_len:
            raise ValueError(
                f"cache_len {cache_len} exceeds max_seq_len {cfg.max_seq_len}"
            )
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        # Embed's use-site replication is what keeps the multichip dryrun
        # free of involuntary full remats: the gather output inherits the
        # batch layout from the tokens, not the table's feature split.
        x = Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.dtype, name="embed"
        )(tokens)
        if cfg.scan_layers:
            scan = nn.scan(
                LlamaScanBody,
                variable_axes={"params": 0, "cache": 0, "losses": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast,) * 7,
                length=cfg.n_layers,
            )
            x, _ = scan(cfg, name="layers_scan")(
                x, positions, segment_ids, decode, mask_bias, token_mask,
                cache_len, cache_slots,
            )
        else:
            block = LlamaBlock
            if cfg.remat and cfg.remat_mode == "block":
                # static: decode flag (4) and cache bucket size (7).
                block = nn.remat(LlamaBlock, static_argnums=(4, 7))
            for i in range(cfg.n_layers):
                x = block(cfg, name=f"layer_{i}")(
                    x, positions, segment_ids, decode, mask_bias, token_mask,
                    cache_len, cache_slots,
                )
        x = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="final_norm")(x)
        head = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32, name="lm_head"
        )
        if return_hidden:
            # Long-context path (train/steps.py chunked_cross_entropy): the
            # caller applies the head per sequence chunk so the full
            # [B, S, vocab] f32 logits never materialize — at 1.36B/32k
            # that single tensor is 4.2 GB, the difference between
            # compiling and not.  Applying the head to ONE position keeps
            # the param tree identical on both paths (XLA drops the dead
            # 1-position matmul when its output is unused).
            _ = head(x[:, :1])
            return x
        return head(x)


def _factory(name):
    @register_model(name)
    def make(**overrides):
        cfg = dataclasses.replace(CONFIGS[name], **overrides)
        return Llama(cfg)

    make.__name__ = name
    return make


for _n in CONFIGS:
    _factory(_n)
