"""Block-paged KV serving: paged pool + prefix reuse + chunked prefill +
speculative decoding — the PagedAttention/vLLM design grafted onto the
continuous-batching scheduler (models/scheduler.py).

The fixed-slot pool buckets every row to ``slot_len`` cache positions, so
mixed prompt lengths pay the longest-bucket tax in HBM.  Here K/V live in
ONE flat pooled tensor of ``num_pages × page_len`` physical positions per
layer; a row holds exactly ``ceil(len/page_len)`` pages, resolved through
its page table into the flat write/read indices
``layers.PagedSlots`` carries into ``Attention._update_cache``.

    physical pool   [num_pages * page_len, kv_h, d]   (page 0 = null/trash)
    page table      row -> [p3, p7, p1, ...]          (logical page j -> physical)
    read indices    row -> flat positions for all L logical slots
                    (unallocated logical pages point at the null page,
                    which the per-row visibility bias masks to exact zeros)

Three exploits ride on the pages:

* **Prefix sharing** — page-sized chunks of the raw prompt hash into a
  trie (``PrefixCache``); identical prefixes map to the SAME read-only
  physical pages, prefilled once.  Copy-on-write is by construction:
  shared pages are never written after insertion (decode writes start at
  the padded prompt length, past every fully-real prompt page), so
  divergence lands in the row's own fresh pages.
* **Chunked prefill** — the un-shared prompt suffix prefills in
  ``KFT_SERVE_PREFILL_CHUNK``-token chunks interleaved with decode
  quanta, so a long admission never stalls the pool.
* **Speculative decoding** — a small draft model (same vocab) proposes
  ``KFT_SERVE_SPEC_TOKENS`` greedy tokens per step from its own paged
  pool (same page-table geometry, lockstep pointers); ONE target pass
  over [current, d_1..d_k] verifies them.  Greedy acceptance emits the
  longest prefix where d_i == argmax(target logits) plus the bonus
  token, which is provably the exact target-greedy stream — a rejected
  draft still yields one correct token.  Spec steps run only while every
  live row is greedy (temperature 0); sampled rows fall back to the
  normal quantum, which is always token-correct.

Token equality vs the sequential path is byte-for-byte (greedy and
seeded sampling): gathers preserve logical order, masked positions
contribute exact zeros (the -1e30 bias underflows exp to 0.0), and the
first-token sampling replays ``generate._prefill_parts``' rng recipe op
for op.  Pinned by tests/test_scheduler.py's paged matrix.

GSPMD: pass ``mesh`` and the flat pool shards over the POOL-POSITION
axis across the data axes (parallel/sharding.page_pool_spec), with
``num_pages`` rounded up so shard boundaries align with page boundaries
— the host-side page tables, free list and prefix trie are untouched
(they only ever produce flat int indices, and gathers/scatters through
them partition like any other indexed op).  Per-lane arrays stay
replicated: lanes are the (tiny) batch axis of the compiled step, and
splitting them would couple lane count to mesh shape.  Speculative
decoding is the one unsupported combination (the draft pool's lockstep
mirroring is not mesh-aware yet) — a draft model plus a mesh raises at
construction, and serve.py records the fallback reason.

``KFT_SERVE_PAGED=0`` falls back to the fixed-slot DecodeScheduler
unchanged (``serve_paged_fallback_total`` counts why — see
docs/serving.md "Sharded paged serving").
"""
from __future__ import annotations

import functools
import math
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.layers import PagedSlots
from kubeflow_tpu.models.scheduler import (
    DEFAULT_PRIORITY,
    DecodeScheduler,
    PendingRequest,
    _Inflight,
    _NEG_INF,
    _Slot,
)
from kubeflow_tpu.platform import config


def _read_indices(page_rows: jax.Array, *, page_len: int) -> jax.Array:
    """[W, M] physical page ids -> [W, M*page_len] flat pool positions."""
    W = page_rows.shape[0]
    return (page_rows[:, :, None] * page_len
            + jnp.arange(page_len)[None, None, :]).reshape(W, -1)


@functools.partial(
    jax.jit,
    static_argnames=("model", "lanes", "slot_len", "pool_positions"),
)
def _init_paged_pool(model, params, *, lanes, slot_len, pool_positions):
    """Build the flat paged cache pytree by running one (discarded)
    paged decode step — the flax ``paged_key``/``paged_value`` variables
    initialize to zeros at [pool_positions, kv_h, d] per layer.  All the
    step's writes land on the null page (trash by definition)."""
    from kubeflow_tpu.models.quantize import dequantize_params

    p = dequantize_params(params)
    ps = PagedSlots(
        write=jnp.zeros((lanes, 1), jnp.int32),
        read=jnp.zeros((lanes, slot_len), jnp.int32),
        pool_positions=pool_positions,
    )
    _, state = model.apply(
        {"params": p}, jnp.zeros((lanes, 1), jnp.int32),
        positions=jnp.zeros((lanes, 1), jnp.int32),
        decode=True, cache_len=slot_len,
        mask_bias=jnp.zeros((lanes, 1, 1, slot_len), jnp.float32),
        cache_slots=ps, mutable=["cache"],
    )
    return state["cache"]


@functools.partial(jax.jit, static_argnames=("model",), donate_argnums=(1,))
def _prefill_chunk(model, cache, params, tokens, positions, paged_slots,
                   chunk_start, pad_rows, lengths, last_logits):
    """One chunked-prefill pass: ``tokens`` [b, c] land at logical slots
    [chunk_start, chunk_start + c) of each row's paged region.  Returns
    ``(cache, last_logits)`` where row i's last-valid-token logits are
    captured when slot ``lengths[i] - 1`` falls inside this chunk.

    The bias is causal-by-logical-slot + the row's prompt-padding holes
    — the same effective mask ``generate._prefill_parts`` applies (its
    built-in causal bias + pad_bias), so the chunk-at-a-time logits
    equal the one-pass prefill's bit for bit."""
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    b, c = tokens.shape
    L = pad_rows.shape[-1]
    k_pos = jnp.arange(L)
    q_slots = chunk_start + jnp.arange(c)                   # [c]
    allowed = k_pos[None, :] <= q_slots[:, None]            # [c, L]
    bias = (jnp.where(allowed, 0.0, _NEG_INF)[None, :, :]
            + pad_rows[:, None, :])[:, None]                # [b, 1, c, L]
    logits, state = model.apply(
        {"params": params, "cache": cache}, tokens,
        positions=positions, decode=True, mask_bias=bias,
        cache_len=L, cache_slots=paged_slots, mutable=["cache"],
    )
    last_idx = (lengths - 1) - chunk_start                  # [b]
    in_chunk = (last_idx >= 0) & (last_idx < c)
    idx = jnp.clip(last_idx, 0, c - 1)
    picked = jnp.take_along_axis(
        logits, jnp.broadcast_to(idx[:, None, None],
                                 (b, 1, logits.shape[-1])), axis=1)[:, 0]
    last_logits = jnp.where(in_chunk[:, None], picked, last_logits)
    return state["cache"], last_logits


@functools.partial(jax.jit, static_argnames=("sampled",))
def _sample_first(last_logits, rng, temps, top_ks, eos_ids, has_eos, *,
                  sampled):
    """First-token sampling from accumulated last-valid logits — op for
    op the tail of ``generate._prefill_parts`` (split(rng, b) → per-row
    split → sample_logits_rows), so the paged first token is
    byte-identical to the sequential path's."""
    from kubeflow_tpu.models.generate import (
        sample_logits_rows, split_row_rngs)

    b = last_logits.shape[0]
    row_rngs, subs = split_row_rngs(jax.random.split(rng, b))
    first = sample_logits_rows(last_logits, subs, temps=temps,
                               top_ks=top_ks, sampled=sampled)
    done0 = has_eos & (first == eos_ids)
    return first, row_rngs, done0


@functools.partial(
    jax.jit,
    static_argnames=("model", "quantum", "sampled", "page_len",
                     "pool_positions", "pool_ns"),
    donate_argnums=(1,),
)
def _paged_pool_steps(model, cache, params, token, pos, write, rngs, done,
                      pad_rows, page_rows, temps, top_ks, eos_ids, has_eos,
                      *, quantum, sampled, page_len, pool_positions,
                      pool_ns=None):
    """``quantum`` decode steps over the paged pool — the exact
    ``scheduler._pool_steps`` body with the per-row write index resolved
    through the page table into flat pool positions.  Vacated lanes keep
    stepping as zombies; the host zeroes their page-table rows at
    eviction, so zombie writes land on the null page and can never
    corrupt a reallocated page."""
    from kubeflow_tpu.models.generate import decode_step
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    W, L = pad_rows.shape
    k_pos = jnp.arange(L)
    rows = jnp.arange(W)
    read = _read_indices(page_rows, page_len=page_len)

    def step(carry, _):
        cache, token, pos, write, rngs, done = carry
        slots = jnp.minimum(write, L - 1)
        flat_w = (page_rows[rows, slots // page_len] * page_len
                  + slots % page_len)
        allowed = k_pos[None, :] <= slots[:, None]
        bias = (jnp.where(allowed, 0.0, _NEG_INF)[:, None, None, :]
                + pad_rows[:, None, None, :])
        ps = PagedSlots(write=flat_w[:, None], read=read,
                        pool_positions=pool_positions,
                        pool_sharding=pool_ns)
        cache, nxt, pos, rngs, done = decode_step(
            model, params, cache, token, pos, rngs, done, bias,
            cache_len=L, temps=temps, top_ks=top_ks, eos_ids=eos_ids,
            has_eos=has_eos, sampled=sampled, cache_slots=ps,
        )
        return (cache, nxt, pos, write + 1, rngs, done), (nxt, done)

    carry = (cache, token, pos, write, rngs, done)
    (cache, token, pos, write, rngs, done), (toks, dones) = jax.lax.scan(
        step, carry, None, length=quantum)
    return cache, token, pos, write, rngs, done, toks, dones


@functools.partial(
    jax.jit,
    static_argnames=("model", "k", "page_len", "pool_positions"),
    donate_argnums=(1,),
)
def _draft_propose(model, cache, params, token, pos, write, pad_rows,
                   page_rows, *, k, page_len, pool_positions):
    """k+1 greedy draft steps from the draft's paged pool: steps 1..k
    propose d_1..d_k; the extra (k+1)-th step's proposal is discarded —
    it exists so the draft cache covers slot write+k and stays hole-free
    when the target accepts all k (the next spec step would otherwise
    attend a never-written slot).  Rejected-tail writes go stale but are
    overwritten by the very step that next reaches their slot, before
    any query can see them."""
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    W, L = pad_rows.shape
    k_pos = jnp.arange(L)
    rows = jnp.arange(W)
    read = _read_indices(page_rows, page_len=page_len)

    def step(carry, _):
        cache, tok, pos, write = carry
        slots = jnp.minimum(write, L - 1)
        flat_w = (page_rows[rows, slots // page_len] * page_len
                  + slots % page_len)
        allowed = k_pos[None, :] <= slots[:, None]
        bias = (jnp.where(allowed, 0.0, _NEG_INF)[:, None, None, :]
                + pad_rows[:, None, None, :])
        ps = PagedSlots(write=flat_w[:, None], read=read,
                        pool_positions=pool_positions)
        logits, state = model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions=pos[:, None], decode=True, mask_bias=bias,
            cache_len=L, cache_slots=ps, mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (state["cache"], nxt, pos + 1, write + 1), nxt

    (cache, _, _, _), outs = jax.lax.scan(
        step, (cache, token, pos, write), None, length=k + 1)
    return cache, outs[:k].T                                # [W, k]


@functools.partial(
    jax.jit,
    static_argnames=("model", "page_len", "pool_positions"),
    donate_argnums=(1,),
)
def _spec_verify(model, cache, params, token, drafts, pos, write, pad_rows,
                 page_rows, *, page_len, pool_positions):
    """ONE target pass over [current, d_1..d_k] per row (k+1 query
    positions, per-position causal visibility): returns the greedy
    next-token at every position and the longest accepted prefix length.
    Row i emits greedy[i, :accepted+1] — the accepted drafts ARE
    greedy[:accepted] by the match definition, plus the free bonus
    token, so the emitted stream is exactly target-greedy."""
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    W, L = pad_rows.shape
    k = drafts.shape[1]
    k_pos = jnp.arange(L)
    seq = jnp.concatenate([token[:, None], drafts], axis=1)   # [W, k+1]
    positions = pos[:, None] + jnp.arange(k + 1)[None, :]
    slots = jnp.minimum(write[:, None] + jnp.arange(k + 1)[None, :], L - 1)
    flat_w = (page_rows[jnp.arange(W)[:, None], slots // page_len]
              * page_len + slots % page_len)                  # [W, k+1]
    read = _read_indices(page_rows, page_len=page_len)
    allowed = k_pos[None, None, :] <= slots[:, :, None]       # [W, k+1, L]
    bias = (jnp.where(allowed, 0.0, _NEG_INF)
            + pad_rows[:, None, :])[:, None]                  # [W,1,k+1,L]
    ps = PagedSlots(write=flat_w, read=read,
                    pool_positions=pool_positions)
    logits, state = model.apply(
        {"params": params, "cache": cache}, seq, positions=positions,
        decode=True, mask_bias=bias, cache_len=L, cache_slots=ps,
        mutable=["cache"],
    )
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [W, k+1]
    match = (drafts == greedy[:, :k]).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(match, axis=1), axis=1)    # [W]
    return state["cache"], greedy, accepted


class PageAllocator:
    """Host-side free list of physical pages with refcounts.  Page 0 is
    reserved as the null/trash page: unallocated logical pages and
    zombie-lane writes resolve to it, always behind the visibility
    mask."""

    def __init__(self, total_pages: int):
        if total_pages < 2:
            raise ValueError(
                f"paged pool needs >= 2 pages (1 null + 1 usable), got "
                f"{total_pages}")
        self.total = total_pages
        self._free = list(range(total_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def allocated_count(self) -> int:
        return len(self._refs)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None when the pool is short
        (caller retries after evictions free pages)."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        return out

    def retain(self, pages):
        for p in pages:
            self._refs[p] += 1

    def release(self, pages):
        for p in pages:
            n = self._refs[p] - 1
            if n < 0:
                raise AssertionError(f"page {p} over-released")
            if n == 0:
                del self._refs[p]
                self._free.append(p)
            else:
                self._refs[p] = n


class _PrefixNode:
    __slots__ = ("page", "chunk", "parent", "children", "row_refs",
                 "last_use")

    def __init__(self, page, chunk, parent):
        self.page = page
        self.chunk = chunk
        self.parent = parent
        self.children: dict = {}
        self.row_refs = 0
        self.last_use = 0


class PrefixCache:
    """Hash-keyed trie over page-sized chunks of RAW prompt tokens:
    node at depth j maps chunk j to the physical page holding its K/V.
    Exact token tuples are the dict keys, so a hash collision can never
    serve the wrong prefix.  Each node holds one allocator reference on
    its page; live rows additionally pin nodes via ``row_refs``.  Under
    page pressure, unpinned LEAF nodes evict in LRU order (leaf-first
    keeps every cached chain walkable from the root)."""

    def __init__(self, allocator: PageAllocator, page_len: int):
        self.alloc = allocator
        self.page_len = page_len
        self._root: dict = {}
        self._nodes: List[_PrefixNode] = []
        self._clock = 0
        self.hits = 0       # pages served from the cache
        self.misses = 0     # lookup-eligible pages that had to prefill

    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def page_ids(self) -> set:
        return {n.page for n in self._nodes}

    def lookup(self, tokens, cap: int) -> Tuple[List[_PrefixNode], List[int]]:
        """Walk the trie over ``tokens``' page-sized chunks, at most
        ``cap`` deep.  Returns (nodes, physical pages) of the longest
        cached prefix."""
        self._clock += 1
        nodes: List[_PrefixNode] = []
        pages: List[int] = []
        level = self._root
        i = 0
        p = self.page_len
        while len(nodes) < cap and i + p <= len(tokens):
            node = level.get(tuple(tokens[i:i + p]))
            if node is None:
                break
            node.last_use = self._clock
            nodes.append(node)
            pages.append(node.page)
            level = node.children
            i += p
        return nodes, pages

    def acquire(self, nodes):
        for n in nodes:
            n.row_refs += 1

    def release(self, nodes):
        for n in nodes:
            n.row_refs -= 1

    def insert(self, tokens, pages, parent_nodes, upto: int):
        """Insert chunks [len(parent_nodes), upto) of ``tokens``, whose
        physical pages are ``pages`` (the row's FULL page table, shared
        prefix included).  Only fully-real prompt pages are eligible
        (``upto = len // page_len``); the cache takes one allocator ref
        per newly inserted page."""
        level = self._root if not parent_nodes else parent_nodes[-1].children
        parent = parent_nodes[-1] if parent_nodes else None
        p = self.page_len
        self._clock += 1
        for j in range(len(parent_nodes), upto):
            chunk = tuple(tokens[j * p:(j + 1) * p])
            node = level.get(chunk)
            if node is None:
                node = _PrefixNode(pages[j], chunk, parent)
                node.last_use = self._clock
                self.alloc.retain([pages[j]])
                level[chunk] = node
                self._nodes.append(node)
            else:
                node.last_use = self._clock
            level = node.children
            parent = node

    def evict_for(self, needed: int):
        """Evict unpinned LRU leaves until the allocator can serve
        ``needed`` pages (best effort — pinned chains stay)."""
        while self.alloc.free_count < needed:
            victims = [n for n in self._nodes
                       if not n.children and n.row_refs == 0]
            if not victims:
                return
            v = min(victims, key=lambda n: n.last_use)
            parent_map = v.parent.children if v.parent else self._root
            del parent_map[v.chunk]
            self._nodes.remove(v)
            self.alloc.release([v.page])


class _PagedSlot(_Slot):
    """One pool lane under the paged engine: the base bookkeeping plus
    the row's page table, its own (releasable) pages, and the prefix
    nodes it pins."""

    __slots__ = ("pages", "own_pages", "nodes")


class _PrefillState:
    """The in-progress chunked admission: one request's rows prefill
    chunk by chunk, interleaved with decode quanta.  The request stays
    at the head of the scheduler queue until this completes, so a loop
    crash can always reach it through ``_fail_outstanding``."""

    __slots__ = ("req", "tokens_np", "positions_np", "lengths", "padded",
                 "n", "pages", "own_pages", "nodes", "shared", "cursor",
                 "last_logits", "pad_np", "read_np", "sampling")

    def __init__(self, req):
        self.req = req


class PagedDecodeScheduler(DecodeScheduler):
    """DecodeScheduler with the block-paged pool engine.  Same public
    surface (submit / stats / stop, the crash-fallback contract, the
    admitted == evicted + active balance), different cache economics:

      page_len      KFT_SERVE_PAGE_LEN      tokens per page (default 64;
                                            slot_len must divide evenly)
      num_pages     KFT_SERVE_PAGES         physical pages incl. the null
                                            page (default: the fixed
                                            pool's capacity, slots x
                                            slot_len / page_len, + 1)
      prefill_chunk KFT_SERVE_PREFILL_CHUNK tokens per admission prefill
                                            pass (0 = whole suffix at
                                            once; default 512)
      spec_tokens   KFT_SERVE_SPEC_TOKENS   draft tokens per speculative
                                            step (default 4; active only
                                            with a draft model)
      prefix_cache  KFT_SERVE_PREFIX_CACHE  prefix-page sharing on/off

    ``slots`` remains the static batch width of the compiled pool step
    (lanes); pages are the memory currency — a short row in a lane holds
    2 pages while a long one holds 30, where the fixed pool charged both
    the full slot_len.
    """

    def __init__(self, model, params, *, slots=None, slot_len=None,
                 quantum=None, mesh=None, pipeline=None, telemetry=None,
                 page_len=None, num_pages=None, prefill_chunk=None,
                 spec_tokens=None, draft_model=None, draft_params=None,
                 prefix_cache=None):
        if mesh is not None and draft_model is not None:
            # The draft pool mirrors the target's pages in lockstep from
            # host-built chunk slots; that mirroring is not mesh-aware
            # yet, and a silently-replicated draft pool would defeat the
            # sharding.  serve.py catches this and records the fallback
            # reason (spec-decode-mesh).
            raise ValueError(
                "speculative decoding under a mesh is not supported: "
                "drop --draft-model or the mesh (serve.py falls back to "
                "the fixed-slot scheduler for this combination)")
        super().__init__(model, params, slots=slots, slot_len=slot_len,
                         quantum=quantum, mesh=mesh, pipeline=pipeline,
                         telemetry=telemetry)
        # GSPMD pool layout (module docstring): the flat pool shards
        # over its pool-position axis across the data axes; a tp-only
        # mesh has no data axis, so the pool stays replicated (shards=1)
        # while the params still run tensor-parallel.
        self._page_ns = None
        self.pool_shards = 1
        if mesh is not None:
            from kubeflow_tpu.parallel.sharding import (
                page_pool_shards,
                page_pool_sharding,
            )

            self.pool_shards = page_pool_shards(mesh)
            if self.pool_shards > 1:
                self._page_ns = page_pool_sharding(mesh)
        self.page_len = page_len or config.knob(
            "KFT_SERVE_PAGE_LEN", 64, int,
            doc="Paged-KV page size in tokens (models/paged.py); the "
                "serve slot length must be a multiple of it",
            validate=lambda v: None if 1 <= v <= 4096
            else "must be in [1, 4096]")
        if self.page_len < 1 or self.slot_len % self.page_len:
            raise ValueError(
                f"KFT_SERVE_PAGE_LEN {self.page_len} must be a positive "
                f"divisor of slot_len {self.slot_len} — a bad page size "
                f"must fail loudly, not quietly serve the fallback path")
        self.max_pages_row = self.slot_len // self.page_len
        default_pages = self.slots * self.max_pages_row + 1
        self.num_pages = num_pages or config.knob(
            "KFT_SERVE_PAGES", 0, int,
            doc="Physical KV pages in the paged pool, null page "
                "included (0 = the fixed pool's capacity + 1)",
            validate=lambda v: None if v >= 0 else "must be >= 0",
        ) or default_pages
        if self.num_pages < self.max_pages_row + 1:
            raise ValueError(
                f"KFT_SERVE_PAGES {self.num_pages} cannot hold one "
                f"full-length row ({self.max_pages_row} pages) plus the "
                f"null page")
        if self.num_pages % self.pool_shards:
            # Round UP to a shard multiple: every shard then holds whole
            # pages (the page-axis sharding rule — a page never
            # straddles devices) and the pool axis divides evenly at
            # device_put.  Extra pages only add capacity.
            self.num_pages += (self.pool_shards
                               - self.num_pages % self.pool_shards)
        self.pool_positions = self.num_pages * self.page_len
        self.prefill_chunk = prefill_chunk if prefill_chunk is not None \
            else config.knob(
                "KFT_SERVE_PREFILL_CHUNK", 512, int,
                doc="Chunked-prefill pass size in tokens (0 = whole "
                    "prompt suffix in one pass)",
                validate=lambda v: None if v >= 0 else "must be >= 0")
        self.spec_tokens = spec_tokens if spec_tokens is not None \
            else config.knob(
                "KFT_SERVE_SPEC_TOKENS", 4, int,
                doc="Draft tokens proposed per speculative-decoding "
                    "step (needs --draft-model; 0 disables)",
                validate=lambda v: None if 0 <= v <= 64
                else "must be in [0, 64]")
        if not (0 <= self.spec_tokens <= 64):
            raise ValueError(
                f"KFT_SERVE_SPEC_TOKENS {self.spec_tokens} outside "
                f"[0, 64]")
        self.draft_model = draft_model
        self.draft_params = draft_params
        if draft_model is not None:
            if draft_model.cfg.vocab_size != model.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.cfg.vocab_size} != target "
                    f"vocab {model.cfg.vocab_size}: speculative proposals "
                    f"would index a different token space")
            if draft_model.cfg.max_seq_len < self.slot_len:
                raise ValueError(
                    f"draft max_seq_len {draft_model.cfg.max_seq_len} < "
                    f"slot_len {self.slot_len}")
        use_prefix = prefix_cache if prefix_cache is not None else \
            config.env_bool("KFT_SERVE_PREFIX_CACHE", True)
        self.allocator = PageAllocator(self.num_pages)
        self.prefix = (PrefixCache(self.allocator, self.page_len)
                       if use_prefix else None)
        self._lane_pages: List[List[int]] = [[] for _ in range(self.slots)]
        self._prefilling: Optional[_PrefillState] = None
        self._draft_cache = None
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0

    # -- sizing -----------------------------------------------------------

    def _spec_slack(self) -> int:
        """Extra write slots a speculative verify can touch past the last
        budgeted token — reserved so verify reads within budget never
        resolve to the (clobbered) null page."""
        if self.draft_model is None or self.spec_tokens < 1:
            return 0
        return self.spec_tokens + 1

    def _pages_per_row(self, padded: int, n: int) -> int:
        need = padded + n - 1 + self._spec_slack()
        return min(math.ceil(need / self.page_len), self.max_pages_row)

    def submit(self, rows, *, max_new_tokens, temperature=0.0, top_k=None,
               eos_token=None, seed=0, tokens=None, prompt_mask=None,
               priority=DEFAULT_PRIORITY, deadline=None):
        longest = max(len(r) for r in rows)
        if longest + max_new_tokens <= self.slot_len:
            # Worst-case page demand (no prefix reuse) must fit the pool,
            # or admission would stall forever; the slot_len bound above
            # keeps the base class's error for oversized rows.
            need = self._pages_per_row(longest, max_new_tokens) * len(rows)
            if need > self.num_pages - 1:
                raise ValueError(
                    f"request needs up to {need} KV pages "
                    f"({len(rows)} rows x "
                    f"{self._pages_per_row(longest, max_new_tokens)}), "
                    f"pool has {self.num_pages - 1} usable "
                    f"(KFT_SERVE_PAGES)")
        return super().submit(
            rows, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, eos_token=eos_token, seed=seed, tokens=tokens,
            prompt_mask=prompt_mask, priority=priority, deadline=deadline)

    def stats(self) -> dict:
        out = super().stats()
        out.update({
            "page_len": self.page_len,
            "pages_total": self.num_pages,
            "pages_free": self.allocator.free_count,
            "pages_shared": self.prefix.cached_pages if self.prefix else 0,
            "pages_active": self.allocator.allocated_count
            - (self.prefix.cached_pages if self.prefix else 0),
            "prefix_hits": self.prefix.hits if self.prefix else 0,
            "prefix_misses": self.prefix.misses if self.prefix else 0,
            "spec_proposed": self._spec_proposed_total,
            "spec_accepted": self._spec_accepted_total,
            "pool_shards": self.pool_shards,
        })
        return out

    def debug_pages(self) -> dict:
        """Live page-table snapshot for the soak aliasing check: any two
        lanes' page sets may only overlap inside the declared shared
        (prefix-cache) pages."""
        shared = self.prefix.page_ids() if self.prefix else set()
        lanes = {i: list(pages)
                 for i, pages in enumerate(self._lane_pages)
                 if self._slot_state[i] is not None}
        return {"shared": shared, "lanes": lanes}

    # -- pool -------------------------------------------------------------

    def _ensure_pool(self):
        if self._cache is not None:
            return
        self._cache = _init_paged_pool(
            self.model, self.params, lanes=self.slots,
            slot_len=self.slot_len, pool_positions=self.pool_positions)
        if self._page_ns is not None:
            # Place every pool leaf sharded over its pool-position axis
            # (ndim - 3: leaves are [pool, kv_h, d], or
            # [layers, pool, kv_h, d] under scan_layers).  The in-step
            # scatter output is pinned back to this layout by
            # layers.PagedSlots.pool_sharding, so the pool never
            # silently materializes replicated between quanta.
            from jax.sharding import NamedSharding

            from kubeflow_tpu.parallel.sharding import page_pool_spec

            def place(x):
                return jax.device_put(x, NamedSharding(
                    self.mesh, page_pool_spec(self.mesh, x.ndim)))

            self._cache = jax.tree.map(
                lambda x: place(x) if getattr(x, "ndim", 0) >= 3 else x,
                self._cache)
        self._rngs = jax.random.split(jax.random.key(0), self.slots)
        if self.draft_model is not None and self.spec_tokens >= 1:
            self._draft_cache = _init_paged_pool(
                self.draft_model, self.draft_params, lanes=self.slots,
                slot_len=self.slot_len,
                pool_positions=self.pool_positions)
        self._pad_rows = jnp.full(
            (self.slots, self.slot_len), _NEG_INF, jnp.float32)
        tel = self._telemetry()
        if tel is not None and hasattr(tel, "page_pool_shards"):
            tel.page_pool_shards.set(self.pool_shards)
        self._update_page_metrics()

    def _page_rows_np(self) -> np.ndarray:
        pr = np.zeros((self.slots, self.max_pages_row), np.int32)
        for i, pages in enumerate(self._lane_pages):
            if pages:
                pr[i, :len(pages)] = pages
        return pr

    def _update_page_metrics(self):
        tel = self._telemetry()
        if tel is None or not hasattr(tel, "kv_pages"):
            return
        shared = self.prefix.cached_pages if self.prefix else 0
        active = self.allocator.allocated_count - shared
        tel.kv_pages.labels(state="free").set(self.allocator.free_count)
        tel.kv_pages.labels(state="active").set(active)
        tel.kv_pages.labels(state="shared").set(shared)
        # Fragmentation: capacity reserved by live rows but not yet
        # holding written tokens.  Written positions per lane = the write
        # pointer (clamped to its reservation); the in-flight prefill
        # counts its cursor.
        reserved = 0
        written = 0
        for i, slot in enumerate(self._slot_state):
            if slot is None:
                continue
            cap = len(self._lane_pages[i]) * self.page_len
            reserved += cap
            written += min(slot.write, cap)
        for slot in self._pending_rows:
            cap = len(slot.pages) * self.page_len
            reserved += cap
            written += min(slot.write, cap)
        st = self._prefilling
        if st is not None:
            cap = len(st.pages[0]) * self.page_len * len(st.req.rows)
            reserved += cap
            written += st.cursor * len(st.req.rows)
        frag = 1.0 - written / reserved if reserved else 0.0
        tel.kv_page_fragmentation.set(frag)

    # -- admission --------------------------------------------------------

    def _admit(self):
        """Paged admission: place prefilled rows, then advance the ONE
        in-progress chunked prefill by a single chunk, then (if idle)
        start the next queued request.  Returning with ``_prefilling``
        set yields the device back to ``_run_quantum`` — that is the
        chunked-prefill/decode interleave."""
        while True:
            free = self._free_slots()
            while free and self._pending_rows:
                self._place(self._pending_rows[0], free.pop(0))
                self._pending_rows.pop(0)
            st = self._prefilling
            if st is not None:
                try:
                    self._advance_prefill(st)
                except BaseException as exc:  # noqa: BLE001 — per-request
                    self._abort_prefill(st, exc)
                    continue
                if self._prefilling is not None:
                    return          # mid-prefill: give decode a quantum
                continue            # finished: loop to place its rows
            if self._pending_rows:
                return              # rows wait on lanes, keep decoding
            req = self._next_queued(pop=False)
            if req is None:
                return
            try:
                started = self._begin_prefill(req)
            except BaseException as exc:  # noqa: BLE001 — per-request
                self._drop_queued(req)
                req._fail(exc)
                tel = self._telemetry()
                if tel is not None:
                    tel.queue_depth.dec(len(req.rows))
                continue
            if not started:
                return              # pages short: decode frees them

    def _drop_queued(self, req: PendingRequest):
        with self._cond:
            if req in self._queue:
                self._queue.remove(req)

    def _abort_prefill(self, st: _PrefillState, exc: BaseException):
        self._prefilling = None
        for own in st.own_pages:
            self.allocator.release(own)
        if self.prefix is not None:
            for nodes in st.nodes:
                self.prefix.release(nodes)
        self._drop_queued(st.req)
        st.req._fail(exc)
        tel = self._telemetry()
        if tel is not None:
            tel.queue_depth.dec(len(st.req.rows))
        self._update_page_metrics()

    def _begin_prefill(self, req: PendingRequest) -> bool:
        """Host-side admission start: prefix lookup, page allocation, the
        chunk cursor.  Returns False (request stays queued) when the
        allocator is short even after LRU prefix eviction — decode
        quanta keep running and free pages."""
        rows = req.rows
        b = len(rows)
        n = req.max_new_tokens
        padded = max(len(r) for r in rows)
        p = self.page_len
        if self.prefix is not None:
            caps = [(len(r) - 1) // p for r in rows]
            looked = [self.prefix.lookup(r, cap)
                      for r, cap in zip(rows, caps)]
            # One uniform shared depth across the request's rows keeps
            # the batched chunk pass rectangular; capped so at least one
            # suffix token remains to produce the first-token logits.
            m = min(len(nodes) for nodes, _ in looked)
        else:
            caps = [0] * b
            looked = [([], [])] * b
            m = 0
        total_row = self._pages_per_row(padded, n)
        own_count = total_row - m
        need = own_count * b
        if self.prefix is not None:
            self.prefix.evict_for(need)
        flat = self.allocator.alloc(need)
        if flat is None:
            return False
        st = _PrefillState(req)
        st.shared = m
        st.own_pages = [flat[i * own_count:(i + 1) * own_count]
                        for i in range(b)]
        st.nodes = [nodes[:m] for nodes, _ in looked]
        st.pages = [list(pages[:m]) + st.own_pages[i]
                    for i, (_, pages) in enumerate(looked)]
        if self.prefix is not None:
            for nodes in st.nodes:
                self.prefix.acquire(nodes)
            hit = m * b
            miss = sum(max(cap - m, 0) for cap in caps)
            self.prefix.hits += hit
            self.prefix.misses += miss
            tel = self._telemetry()
            if tel is not None and hasattr(tel, "prefix_cache_hits"):
                if hit:
                    tel.prefix_cache_hits.inc(hit)
                if miss:
                    tel.prefix_cache_misses.inc(miss)
        st.padded = padded
        st.n = n
        st.cursor = m * p
        tokens_np = np.zeros((b, padded), np.int32)
        mask_np = np.zeros((b, padded), bool)
        for i, r in enumerate(rows):
            tokens_np[i, :len(r)] = r
            mask_np[i, :len(r)] = True
        st.tokens_np = tokens_np
        st.positions_np = np.maximum(
            np.cumsum(mask_np.astype(np.int32), axis=-1) - 1, 0)
        st.lengths = jnp.asarray(mask_np.sum(axis=-1).astype(np.int32))
        pad_np = np.zeros((b, self.slot_len), np.float32)
        pad_np[~np.concatenate(
            [mask_np, np.ones((b, self.slot_len - padded), bool)],
            axis=-1)] = _NEG_INF
        st.pad_np = pad_np
        table = np.zeros((b, self.max_pages_row), np.int32)
        for i, pages in enumerate(st.pages):
            table[i, :len(pages)] = pages
        st.read_np = (table[:, :, None] * p
                      + np.arange(p)[None, None, :]).reshape(b, -1)
        from kubeflow_tpu.models.generate import _row_sampling_arrays

        st.sampling = _row_sampling_arrays(
            b, req.temperature, req.top_k, req.eos_token)
        vocab = self.model.cfg.vocab_size
        st.last_logits = jnp.zeros((b, vocab), jnp.float32)
        req.t_admitted = time.perf_counter()
        req.admitted.set()
        self._prefilling = st
        self._update_page_metrics()
        return True

    def _chunk_slots(self, st: _PrefillState, start: int, c: int,
                     model_pool: bool = True) -> PagedSlots:
        p = self.page_len
        slots = np.arange(start, start + c)
        write = np.stack([
            np.asarray(pages, np.int32)[slots // p] * p + slots % p
            for pages in st.pages])
        return PagedSlots(write=jnp.asarray(write, jnp.int32),
                          read=jnp.asarray(st.read_np, jnp.int32),
                          pool_positions=self.pool_positions,
                          pool_sharding=self._page_ns)

    def _advance_prefill(self, st: _PrefillState):
        """One prefill chunk on the device; on the last chunk, sample
        the first token (the sequential rng recipe), run the draft
        prefill, insert shareable pages, and peel rows into pending
        slots."""
        c = st.padded - st.cursor
        if self.prefill_chunk > 0:
            c = min(c, self.prefill_chunk)
        sl = slice(st.cursor, st.cursor + c)
        ps = self._chunk_slots(st, st.cursor, c)
        self._cache, st.last_logits = _prefill_chunk(
            self.model, self._cache, self.params,
            jnp.asarray(st.tokens_np[:, sl]),
            jnp.asarray(st.positions_np[:, sl]), ps,
            jnp.int32(st.cursor), jnp.asarray(st.pad_np), st.lengths,
            st.last_logits)
        st.cursor += c
        if st.cursor < st.padded:
            return
        self._finish_prefill(st)

    def _finish_prefill(self, st: _PrefillState):
        req = st.req
        b = len(req.rows)
        p = self.page_len
        temps, top_ks, eos_ids, has_eos = st.sampling
        first, row_rngs, done0 = _sample_first(
            st.last_logits, jax.random.key(req.seed), temps, top_ks,
            eos_ids, has_eos, sampled=req.temperature != 0.0)
        if self._draft_cache is not None:
            # The draft pool mirrors the target's pages in lockstep: one
            # full-suffix pass fills the same flat slots of the draft's
            # flat tensors, so future spec steps attend a complete
            # draft-side history.
            start = st.shared * p
            sl = slice(start, st.padded)
            ps = self._chunk_slots(st, start, st.padded - start)
            self._draft_cache, _ = _prefill_chunk(
                self.draft_model, self._draft_cache, self.draft_params,
                jnp.asarray(st.tokens_np[:, sl]),
                jnp.asarray(st.positions_np[:, sl]), ps,
                jnp.int32(start), jnp.asarray(st.pad_np), st.lengths,
                jnp.zeros_like(st.last_logits))
        first_h, done_h, lengths_h = jax.device_get(
            (first, done0, st.lengths))
        req.t_first = time.perf_counter()
        req.first_token.set()
        if self.prefix is not None:
            for i, r in enumerate(req.rows):
                self.prefix.insert(r, st.pages[i], st.nodes[i],
                                   len(r) // p)
        self._prefilling = None
        self._drop_queued(req)
        tel = self._telemetry()
        n = st.n
        eos = req.eos_token
        for i in range(b):
            tok0 = int(first_h[i])
            if n == 1 or bool(done_h[i]):
                # Complete at admission: counted admitted AND evicted so
                # the balance invariant holds at every instant; pages
                # release immediately (prefix-inserted ones live on in
                # the cache via its own refs).
                self._admitted_total += 1
                self._evicted_total += 1
                self.allocator.release(st.own_pages[i])
                if self.prefix is not None:
                    self.prefix.release(st.nodes[i])
                if tel is not None:
                    tel.queue_depth.dec(1)
                    tel.scheduler_admitted.inc()
                    tel.scheduler_evicted.inc()
                self._complete_row(req, i, [tok0] + [eos] * (n - 1))
                continue
            slot = _PagedSlot(
                req, i, token=tok0, pos=int(lengths_h[i]),
                write=st.padded, done=False, budget=n - 1)
            slot.pages = st.pages[i]
            slot.own_pages = st.own_pages[i]
            slot.nodes = st.nodes[i]
            slot._rng_src = (row_rngs, i)
            slot._pad_row = st.pad_np[i]
            self._pending_rows.append(slot)
        self._update_page_metrics()

    def _place(self, slot: _PagedSlot, idx: int):
        """Lane placement without a cache copy: the row's K/V already
        live in the pooled tensors — only the page-table row, rng key
        and visibility bias land in the lane."""
        self._lane_pages[idx] = slot.pages
        row_rngs, i = slot._rng_src
        self._rngs = self._rngs.at[idx].set(row_rngs[i])
        self._pad_rows = self._pad_rows.at[idx].set(
            jnp.asarray(slot._pad_row))
        self._admitted_total += 1
        tel = self._telemetry()
        if tel is not None:
            tel.queue_depth.dec(1)
            tel.scheduler_admitted.inc()
            tel.slots_active.set(
                1 + sum(s is not None for s in self._slot_state))
        del slot._rng_src, slot._pad_row
        self._slot_state[idx] = slot
        self._carry = None
        self._update_page_metrics()

    # -- decode -----------------------------------------------------------

    def _spec_ready(self) -> bool:
        """Speculative steps need a draft pool, all-greedy live rows
        (greedy acceptance is exact only against argmax), and k+1 slots
        of reserved headroom on every row so verify reads stay inside
        owned pages."""
        if self._draft_cache is None or self.spec_tokens < 1:
            return False
        k = self.spec_tokens
        any_live = False
        for s in self._slot_state:
            if s is None:
                continue
            any_live = True
            if s.temp != 0.0 or s.write + k + 1 > self.slot_len:
                return False
        return any_live

    def _pre_dispatch_sync(self) -> bool:
        """Paged sync points, on top of the base carry-rebuild rule:
        speculative decisioning (``_spec_ready``) and the spec step
        itself read host write pointers, so with a draft model attached
        the pending harvest always lands first (pipelining then overlaps
        only admission work — the draft path trades overlap for exact
        lockstep pointers)."""
        if self._carry is None or self._draft_cache is not None:
            self._harvest()
        if self._spec_ready():
            self._run_spec_step()
            return True
        return not any(s is not None for s in self._slot_state)

    def _dispatch_quantum(self):
        state = self._slot_state
        if self._carry is None:
            temps = [s.temp if s else 0.0 for s in state]
            self._carry = (
                jnp.asarray([s.token if s else 0 for s in state],
                            jnp.int32),
                jnp.asarray([s.pos if s else 0 for s in state], jnp.int32),
                jnp.asarray([s.write if s else 0 for s in state],
                            jnp.int32),
                jnp.asarray([s.done if s else True for s in state], bool),
                jnp.asarray(temps, jnp.float32),
                jnp.asarray([s.top_k if s else 0 for s in state],
                            jnp.int32),
                jnp.asarray([s.eos if s else 0 for s in state], jnp.int32),
                jnp.asarray([s.has_eos if s else False for s in state],
                            bool),
                any(t != 0.0 for t in temps),
            )
        (token, pos, write, done, temps_d, top_ks_d, eos_d, has_eos_d,
         sampled) = self._carry
        # The page table re-uploads every dispatch (tiny int array): an
        # eviction zeroes its lane row here, redirecting zombie writes
        # to the null page.  Under pipelining one in-flight quantum may
        # still carry the PREVIOUS table — safe: the zombie then writes
        # its own already-released pages, and any new occupant of those
        # pages prefills strictly after it on the device stream (the
        # donated-cache dependency chain), overwriting every position
        # its mask will ever expose.
        page_rows = jnp.asarray(self._page_rows_np())
        (self._cache, token, pos, write, self._rngs, done, toks,
         dones) = _paged_pool_steps(
            self.model, self._cache, self.params,
            token, pos, write, self._rngs, done,
            self._pad_rows, page_rows, temps_d, top_ks_d, eos_d,
            has_eos_d, quantum=self.quantum, sampled=sampled,
            page_len=self.page_len, pool_positions=self.pool_positions,
            pool_ns=self._page_ns,
        )
        self._carry = (token, pos, write, done, temps_d, top_ks_d, eos_d,
                       has_eos_d, sampled)
        if self._t_cycle_mark is None:
            self._t_cycle_mark = time.perf_counter()
        return _Inflight(toks, dones, list(state), self.quantum)

    def _harvest_handle(self, h):
        super()._harvest_handle(h)
        self._update_page_metrics()

    def _run_spec_step(self):
        """One speculative round: k+1 draft steps propose, one target
        pass verifies, the host emits the accepted prefix + bonus token
        per row.  Both pools' write pointers advance by accepted+1 in
        lockstep; the rejected tail needs no rollback — those slots sit
        above the new pointer, masked until the step that overwrites
        them."""
        state = self._slot_state
        k = self.spec_tokens
        token = jnp.asarray([s.token if s else 0 for s in state],
                            jnp.int32)
        pos = jnp.asarray([s.pos if s else 0 for s in state], jnp.int32)
        write = jnp.asarray([s.write if s else 0 for s in state],
                            jnp.int32)
        page_rows = jnp.asarray(self._page_rows_np())
        self._draft_cache, drafts = _draft_propose(
            self.draft_model, self._draft_cache, self.draft_params,
            token, pos, write, self._pad_rows, page_rows,
            k=k, page_len=self.page_len,
            pool_positions=self.pool_positions)
        self._cache, greedy, accepted = _spec_verify(
            self.model, self._cache, self.params, token, drafts, pos,
            write, self._pad_rows, page_rows,
            page_len=self.page_len, pool_positions=self.pool_positions)
        greedy_h, acc_h = jax.device_get((greedy, accepted))
        self._steps_total += 1
        tel = self._telemetry()
        active = sum(s is not None for s in state)
        if tel is not None:
            tel.batch_fill_ratio.observe(active / max(self.slots, 1))
            tel.slots_active.set(active)
        proposed = accepted_n = 0
        for i, slot in enumerate(state):
            if slot is None:
                continue
            a = int(acc_h[i])
            proposed += k
            accepted_n += a
            for j in range(a + 1):
                if len(slot.collected) >= slot.budget:
                    break
                t = int(greedy_h[i, j])
                slot.collected.append(t)
                if slot.has_eos and t == slot.eos:
                    # Sequential semantics: EOS freezes the row; tokens
                    # past it are EOS padding, which eviction fills.
                    slot.done = True
                    break
            slot.token = int(greedy_h[i, a])
            slot.pos += a + 1
            slot.write += a + 1
            if slot.done or len(slot.collected) >= slot.budget:
                self._evict(i)
        self._spec_proposed_total += proposed
        self._spec_accepted_total += accepted_n
        if tel is not None and hasattr(tel, "spec_proposed"):
            if proposed:
                tel.spec_proposed.inc(proposed)
            if accepted_n:
                tel.spec_accepted.inc(accepted_n)
        # Host-side pointers moved: the next normal quantum must rebuild
        # its device carry from the slot bookkeeping.
        self._carry = None
        self._update_page_metrics()

    def _evict(self, idx: int):
        slot = self._slot_state[idx]
        super()._evict(idx)
        # The lane's page-table row zeroes so the zombie lane writes to
        # the null page; only then can the freed pages be reallocated.
        self._lane_pages[idx] = []
        self.allocator.release(slot.own_pages)
        if self.prefix is not None:
            self.prefix.release(slot.nodes)
        self._update_page_metrics()

    def _fail_outstanding(self, exc: BaseException):
        st = self._prefilling
        self._prefilling = None
        super()._fail_outstanding(exc)
        # The in-progress prefill's request was still queued, so the
        # base drain failed it; page bookkeeping is moot on a dead
        # scheduler but released anyway so post-mortem stats read sane.
        if st is not None:
            for own in st.own_pages:
                self.allocator.release(own)
            if self.prefix is not None:
                for nodes in st.nodes:
                    self.prefix.release(nodes)
        self._lane_pages = [[] for _ in range(self.slots)]
        self._update_page_metrics()
