"""T5-style encoder-decoder in Flax — the seq2seq member of the model zoo.

The reference platform ships no model code (SURVEY.md §2.13); this module
completes the family coverage (CNN / ViT / encoder / decoder / MoE /
**encoder-decoder**) for spawned notebooks.  T5 1.1 shape: RMSNorm
pre-norm, relative-position-bucket attention bias (no absolute position
embeddings), gated-GELU feed-forward, untied LM head.

TPU-first notes: the relative bias is computed once per stack from a
static [q_len, k_len] bucket table and shared by every layer (T5's own
scheme — one embedding lookup, reused), so each block stays a pure
matmul+bias pipeline XLA fuses cleanly; all shapes static, encoder padding
handled by additive mask bias.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.layers import Attention, Embed, RMSNorm
from kubeflow_tpu.models.registry import register_model


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    dim: int = 512
    n_encoder_layers: int = 6
    n_decoder_layers: int = 6
    n_heads: int = 8
    head_dim: int = 64
    ffn_dim: int = 1024
    rel_buckets: int = 32
    rel_max_distance: int = 128
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16


CONFIGS = {
    "t5_debug": T5Config(vocab_size=128, dim=32, n_encoder_layers=2,
                         n_decoder_layers=2, n_heads=2, head_dim=16,
                         ffn_dim=64, dtype=jnp.float32),
    "t5_small": T5Config(),
    "t5_base": T5Config(dim=768, n_encoder_layers=12, n_decoder_layers=12,
                        n_heads=12, ffn_dim=2048),
    "t5_large": T5Config(dim=1024, n_encoder_layers=24, n_decoder_layers=24,
                         n_heads=16, ffn_dim=2816),
}


def relative_position_bucket(relative_position: np.ndarray, *,
                             bidirectional: bool, num_buckets: int,
                             max_distance: int) -> np.ndarray:
    """T5 bucket scheme: half the buckets exact, half log-spaced out to
    max_distance.  Static numpy — the table is built at trace time."""
    ret = np.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(np.int32) * num_buckets
        n = np.abs(n)
    else:
        n = np.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    log_ratio = np.log(np.maximum(n, 1) / max_exact) / np.log(
        max_distance / max_exact
    )
    large = max_exact + (log_ratio * (num_buckets - max_exact)).astype(np.int32)
    large = np.minimum(large, num_buckets - 1)
    ret += np.where(is_small, n, large)
    return ret


class RelativeBias(nn.Module):
    """Learned [buckets, heads] embedding → [1, heads, q, k] additive bias."""

    cfg: T5Config
    bidirectional: bool

    def setup(self):
        cfg = self.cfg
        self.rel_embedding = self.param(
            "rel_embedding",
            nn.initializers.normal(stddev=1.0 / np.sqrt(cfg.dim)),
            (cfg.rel_buckets, cfg.n_heads),
        )

    def __call__(self, q_len: int, k_len: int):
        cfg = self.cfg
        ctx = np.arange(q_len)[:, None] - np.arange(k_len)[None, :]
        buckets = relative_position_bucket(
            -ctx, bidirectional=self.bidirectional,
            num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )  # [q, k] static
        bias = self.rel_embedding[jnp.asarray(buckets)]  # [q, k, heads]
        return jnp.transpose(bias, (2, 0, 1))[None]      # [1, heads, q, k]

    def at_position(self, pos, k_len: int):
        """Bias row for ONE query at traced position ``pos`` against keys
        0..k_len-1 → [1, heads, 1, k_len] (causal decode only; future keys
        are masked by the cache bias, their bucket value is irrelevant).

        The distance→bucket map is precomputed with the SAME static numpy
        function the training path uses and indexed with the traced
        position — bit-exact parity with ``__call__`` (a traced float32
        re-derivation measurably disagreed with numpy's float64 at large
        distances, flipping buckets)."""
        cfg = self.cfg
        dist = np.arange(k_len)                          # q_pos - k_pos >= 0
        table = relative_position_bucket(
            -dist, bidirectional=False,
            num_buckets=cfg.rel_buckets, max_distance=cfg.rel_max_distance,
        )                                                # [k_len] static
        n = jnp.clip(pos - jnp.arange(k_len), 0, k_len - 1)
        buckets = jnp.asarray(table)[n]
        bias = self.rel_embedding[buckets]               # [k_len, heads]
        return jnp.transpose(bias, (1, 0))[None, :, None, :]


class GatedGelu(nn.Module):
    hidden_dim: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        g = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype,
                     name="wi_0")(x)
        u = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype,
                     name="wi_1")(x)
        return nn.Dense(dim, use_bias=False, dtype=self.dtype,
                        name="wo")(nn.gelu(g) * u)


class T5EncoderBlock(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.cfg
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="attn_norm")(x)
        h = Attention(
            num_heads=cfg.n_heads, head_dim=cfg.head_dim, dtype=cfg.dtype,
            # T5 attention is unscaled (the scale is folded into init).
            softmax_scale=1.0, name="attn",
        )(h, mask_bias=bias)
        x = x + h
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="mlp_norm")(x)
        return x + GatedGelu(cfg.ffn_dim, cfg.dtype, name="mlp")(h)


class T5DecoderBlock(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x, encoded, self_bias, cross_bias, *,
                 decode=False, max_decode_len=None):
        cfg = self.cfg
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="self_attn_norm")(x)
        h = Attention(
            num_heads=cfg.n_heads, head_dim=cfg.head_dim, causal=True,
            dtype=cfg.dtype, softmax_scale=1.0, name="self_attn",
        )(h, mask_bias=self_bias, decode=decode, max_decode_len=max_decode_len)
        x = x + h
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="cross_attn_norm")(x)
        h = Attention(
            num_heads=cfg.n_heads, head_dim=cfg.head_dim, dtype=cfg.dtype,
            softmax_scale=1.0, name="cross_attn",
        )(h, kv=encoded, mask_bias=cross_bias, decode=decode)
        x = x + h
        h = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype, name="mlp_norm")(x)
        return x + GatedGelu(cfg.ffn_dim, cfg.dtype, name="mlp")(h)


class T5(nn.Module):
    """Returns [batch, target_len, vocab] logits for (source, target) token
    pairs; ``source_mask`` (True = real token) masks encoder padding out of
    both encoder self-attention and decoder cross-attention.

    ``encode`` / ``decode`` are exposed as separate apply methods so
    autoregressive generation runs the encoder ONCE and scans cached
    decoder steps (models/generate.py ``generate_seq2seq``); ``__call__``
    composes them, so training is unchanged.
    """

    cfg: T5Config

    def setup(self):
        cfg = self.cfg
        # Attribute names double as param-tree names: identical to the
        # previous @nn.compact layout (embed, encoder_i, decoder_i, ...).
        self.embed = Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype)
        self.encoder_rel_bias = RelativeBias(cfg, bidirectional=True)
        self.encoder_blocks = [
            T5EncoderBlock(cfg, name=f"encoder_{i}")
            for i in range(cfg.n_encoder_layers)
        ]
        self.encoder_norm = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype)
        self.decoder_rel_bias = RelativeBias(cfg, bidirectional=False)
        self.decoder_blocks = [
            T5DecoderBlock(cfg, name=f"decoder_{i}")
            for i in range(cfg.n_decoder_layers)
        ]
        self.decoder_norm = RMSNorm(eps=cfg.norm_eps, dtype=cfg.dtype)
        self.lm_head = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32
        )

    @staticmethod
    def _pad_bias(shape_source, source_mask):
        """Encoder-padding additive bias from any [b, src_len]-shaped array
        (source tokens or encoded activations' leading dims)."""
        b, src_len = shape_source.shape[0], shape_source.shape[1]
        if source_mask is None:
            source_mask = jnp.ones((b, src_len), dtype=bool)
        return jnp.where(source_mask, 0.0, -1e30)[:, None, None, :]

    def encode(self, source, source_mask: Optional[jnp.ndarray] = None):
        """Encoder stack → [b, src_len, dim] (bidirectional relative bias,
        shared across layers)."""
        src_len = source.shape[1]
        pad = self._pad_bias(source, source_mask)
        x = self.embed(source)
        enc_bias = self.encoder_rel_bias(src_len, src_len) + pad
        for block in self.encoder_blocks:
            x = block(x, enc_bias)
        return self.encoder_norm(x)

    def decode(self, encoded, target, *,
               source_mask: Optional[jnp.ndarray] = None,
               decode: bool = False,
               step=None,
               max_decode_len: Optional[int] = None):
        """Decoder stack over a precomputed ``encoded`` source.

        Training (``decode=False``): full causal pass, static relative
        bias.  Generation (``decode=True``): single-token steps against
        the self-attention KV cache; ``step`` (traced scalar) positions
        the relative bias row, ``max_decode_len`` sizes the cache.
        """
        pad = self._pad_bias(encoded, source_mask)
        y = self.embed(target)
        if decode:
            if step is None or max_decode_len is None:
                raise ValueError("decode=True needs step and max_decode_len")
            self_bias = self.decoder_rel_bias.at_position(step, max_decode_len)
        else:
            tgt_len = target.shape[1]
            self_bias = self.decoder_rel_bias(tgt_len, tgt_len)
        for block in self.decoder_blocks:
            y = block(y, encoded, self_bias, pad,
                      decode=decode, max_decode_len=max_decode_len)
        y = self.decoder_norm(y)
        return self.lm_head(y)

    def __call__(self, source, target, *,
                 source_mask: Optional[jnp.ndarray] = None):
        encoded = self.encode(source, source_mask)
        return self.decode(encoded, target, source_mask=source_mask)


def _factory(name):
    @register_model(name)
    def make(**overrides):
        cfg = dataclasses.replace(CONFIGS[name], **overrides)
        return T5(cfg)

    make.__name__ = name
    return make


for _n in CONFIGS:
    _factory(_n)
