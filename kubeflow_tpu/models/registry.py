"""Tiny model registry so notebooks / bench harnesses can spawn models by name."""
from __future__ import annotations

from typing import Any, Callable, Dict

_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register_model(name: str):
    """Decorator: register a model factory under ``name``."""

    def deco(fn: Callable[..., Any]):
        if name in _REGISTRY:
            raise ValueError(f"model {name!r} already registered")
        _REGISTRY[name] = fn
        return fn

    return deco


def create_model(name: str, **kwargs) -> Any:
    """Instantiate a registered model (a ``flax.linen.Module``)."""
    # Import for registration side effects on first use.
    from kubeflow_tpu.models import bert, llama, resnet, t5, vit  # noqa: F401

    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def list_models() -> list[str]:
    from kubeflow_tpu.models import bert, llama, resnet, t5, vit  # noqa: F401

    return sorted(_REGISTRY)
