"""Cross-request continuous-batching scheduler for decoder-only serving.

The lock-serialized serve path (models/serve.py) batches only rows that
arrive inside ONE request: concurrent users serialize behind the service
lock, and the decode loop runs at whatever batch width the luckiest
request happened to carry.  This module owns the alternative: a fixed
pool of KV-cache slots and ONE running decode loop over it.

    submit ──► queue ──► admit (prefill, request-batched) ──► slots
                                                               │ decode
               evict (EOS / budget) ◄──────────────────────────┘
                 │
                 └──► freed slot refilled from the queue mid-flight

* **Slots.**  ``slots`` rows × ``slot_len`` cache positions, one cache
  pytree shaped like the model's own ("cache" collection leaves grown to
  [slots, slot_len, kv_h, d]).  ``slot_len`` plays the role of the
  bucketed cache length — every admitted request's prompt+budget must
  fit it (ops/pallas/flash_decode.py's block table wants it a multiple
  of 128 on real chips; the default is the model's max_seq_len).
* **Admission.**  A queued request prefills EXACTLY as the sequential
  path does (``generate_prefill`` — same jit, same shapes, shared
  compile cache), then its per-row decode state (cache rows, first
  token, rope position, per-row RNG key, EOS flag) peels apart into free
  slots.  Rows that don't fit yet wait in a pending-insert list and take
  slots as evictions free them.
* **Decode.**  One compiled step (``_pool_steps``: a ``quantum``-length
  ``lax.scan`` over ``generate.decode_step`` with per-row cache slots
  and a per-row visibility bias) advances EVERY active slot one token
  per step.  temperature/top_k/EOS ride as per-row arrays, so one
  executable serves any mix of requests.
* **Eviction.**  A row leaves its slot the moment it has emitted EOS or
  exhausted its budget; the slot's stale cache content needs no scrub —
  the next occupant's visibility mask hides it, and masked slots
  contribute exact zeros to attention.

Token equality: every op in the pool step is row-independent (per-row
sampling keys via ``sample_logits_rows``, per-row cache writes, per-row
masks), and a row's cache layout in its slot is byte-for-byte the layout
the sequential decode would have used (prompt at slots [0, prompt_len),
decode tokens after, extra slots masked to exact-zero contributions).  A
request therefore generates the SAME tokens continuous-batched as it
does alone — greedy and seeded sampling, pinned by
tests/test_scheduler.py.

MoE caveat: capacity-truncated expert routing couples rows of a batch by
construction, so n_experts > 0 models are batch-composition dependent in
ANY batched server (the lock path included); the equality contract holds
for dense decoders.

GSPMD: pass ``mesh`` to run the same loop over a sharded model — params
come pre-sharded (parallel/sharding.shard_params via serve.load_service
--mesh), the slot pool's batch axis is placed with ``batch_sharding``,
and XLA inserts the collectives inside the one compiled step.

Pipelined dispatch (``KFT_SERVE_PIPELINE``, default on): the loop
dispatches quantum N+1 from the device-resident carry BEFORE blocking on
quantum N's host-visible tokens, so Python bookkeeping (token
collection, eviction, admission prep) overlaps device execution instead
of serializing with it.  At most ONE quantum is un-harvested; the
harvest credits tokens against a dispatch-time slot snapshot (a lane
re-occupied mid-flight can never inherit its predecessor's zombie
tokens), and any sync point that reads host pointers — carry rebuild
after an admission, speculative steps — harvests first.  Token streams
are byte-identical to the synchronous loop: the carry chains purely on
device, and an eviction merely lands one harvest later (pinned by
tests/test_paged.py's determinism A/B).
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.platform import config

_NEG_INF = -1e30

# Request priority classes, lowest value admitted first.  The names are
# the wire vocabulary (X-KFT-Priority header, activator fair-share) and
# the ints are the admission order — FIFO within a class, so a flood of
# batch work can never starve interactive requests of ADMISSION (decode
# slots already held are never preempted).
PRIORITY_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}
DEFAULT_PRIORITY = PRIORITY_CLASSES["standard"]


class DeadlineExceeded(RuntimeError):
    """The request's deadline budget (X-KFT-Deadline-Seconds) ran out
    while it was still queued — the scheduler evicts it at selection
    time instead of spending prefill/decode on a client that has already
    given up.  models/serve.py maps this to a structured 504."""


@functools.partial(
    jax.jit,
    static_argnames=("model", "quantum", "sampled"),
    donate_argnums=(1,),
)
def _pool_steps(model, cache, params, token, pos, write, rngs, done,
                pad_rows, temps, top_ks, eos_ids, has_eos, *,
                quantum, sampled):
    """``quantum`` decode steps over the whole slot pool in one
    executable.  Returns ``(cache, rngs, toks [quantum, slots],
    dones [quantum, slots])``.

    Built from the exact sequential step body (generate.decode_step);
    the only differences are mechanical: per-row cache writes at
    ``write`` (the flax scalar index can't express rows at different
    depths) and the causal visibility computed per row instead of from
    that scalar — the bias VALUES at every live slot are identical to
    the sequential run's, which is what keeps outputs token-equal."""
    from kubeflow_tpu.models.generate import decode_step
    from kubeflow_tpu.models.quantize import dequantize_params

    params = dequantize_params(params)
    S = pad_rows.shape[-1]
    k_pos = jnp.arange(S)

    def step(carry, _):
        cache, token, pos, write, rngs, done = carry
        # Finished rows keep stepping until the host evicts them; clamp
        # their (discarded) writes into range.
        slots = jnp.minimum(write, S - 1)
        allowed = k_pos[None, :] <= slots[:, None]
        bias = (jnp.where(allowed, 0.0, _NEG_INF)[:, None, None, :]
                + pad_rows[:, None, None, :])
        cache, nxt, pos, rngs, done = decode_step(
            model, params, cache, token, pos, rngs, done, bias,
            cache_len=S, temps=temps, top_ks=top_ks, eos_ids=eos_ids,
            has_eos=has_eos, sampled=sampled, cache_slots=slots,
        )
        return (cache, nxt, pos, write + 1, rngs, done), (nxt, done)

    carry = (cache, token, pos, write, rngs, done)
    (cache, token, pos, write, rngs, done), (toks, dones) = jax.lax.scan(
        step, carry, None, length=quantum)
    # The final carry feeds the NEXT quantum directly (no host→device
    # rebuild) whenever no admission changed the pool in between.
    return cache, token, pos, write, rngs, done, toks, dones


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _place_row(pool_cache, rngs, pad_rows, req_cache, req_rngs, pad_row,
               slot, row):
    """Copy request-cache row ``row`` into pool slot ``slot``, and land
    the row's RNG key and visibility-bias row in the same dispatch (ONE
    executable per placement — admission churn is on the serving hot
    path).

    K/V leaves are [b, L, kv_h, d] (or [layers, b, L, kv_h, d] under
    scan_layers), so the batch axis is ``ndim - 4``; lower-rank leaves
    (the scalar cache_index) pass through untouched.  ``slot``/``row``
    are traced, so ONE compile per request-cache shape covers every
    placement.  L <= slot_len: positions past L keep the previous
    occupant's bytes, which the visibility mask turns into exact zeros."""

    def one(p, r):
        if getattr(r, "ndim", 0) < 4:
            return p
        axis = r.ndim - 4
        starts_r = [0] * r.ndim
        starts_r[axis] = row
        sizes = list(r.shape)
        sizes[axis] = 1
        sliced = jax.lax.dynamic_slice(r, starts_r, sizes)
        starts_p = [0] * p.ndim
        starts_p[axis] = slot
        return jax.lax.dynamic_update_slice(p, sliced.astype(p.dtype),
                                            starts_p)

    pool_cache = jax.tree.map(one, pool_cache, req_cache)
    rngs = rngs.at[slot].set(req_rngs[row])
    pad_rows = jax.lax.dynamic_update_slice(
        pad_rows, pad_row[None], (slot, 0))
    return pool_cache, rngs, pad_rows


@functools.partial(jax.jit,
                   static_argnames=("model", "slots", "slot_len"))
def _init_pool(model, params, *, slots, slot_len):
    """Build the pool cache pytree by running one (discarded) decode step
    at the pool shape — the flax cache variables initialize to zeros at
    [slots, slot_len, ...]; the garbage this step writes at position 0
    is behind every future occupant's mask."""
    from kubeflow_tpu.models.quantize import dequantize_params

    p = dequantize_params(params)
    _, state = model.apply(
        {"params": p}, jnp.zeros((slots, 1), jnp.int32),
        positions=jnp.zeros((slots, 1), jnp.int32),
        decode=True, cache_len=slot_len, mutable=["cache"],
    )
    return state["cache"]


class PendingRequest:
    """Submit-side handle: the request thread waits on the lifecycle
    events (admitted → first token → done) while the scheduler thread
    drives them; ``result()`` returns the row lists or re-raises the
    scheduler-side error."""

    def __init__(self, rows, *, max_new_tokens, temperature, top_k,
                 eos_token, seed, priority=DEFAULT_PRIORITY,
                 deadline=None):
        self.rows = rows
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_token = eos_token
        self.seed = seed
        self.priority = priority    # admission class, lower admits first
        self.deadline = deadline    # absolute time.monotonic() cutoff
        self.tokens = None          # optional pre-padded [b, L] prompt
        self.prompt_mask = None     # optional [b, L] validity mask
        self.outputs: List[Optional[list]] = [None] * len(rows)
        self.remaining = len(rows)
        self.error: Optional[BaseException] = None
        self.admitted = threading.Event()
        self.first_token = threading.Event()
        self.done = threading.Event()
        self.t_admitted: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None

    def _fail(self, exc: BaseException):
        self.error = exc
        self.admitted.set()
        self.first_token.set()
        self.done.set()

    def wait_admitted(self):
        self.admitted.wait()
        if self.error is not None:
            raise self.error

    def wait_first_token(self):
        self.first_token.wait()
        if self.error is not None:
            raise self.error

    def result(self) -> List[list]:
        self.done.wait()
        if self.error is not None:
            raise self.error
        return list(self.outputs)


class _Slot:
    """Host-side bookkeeping for one pool row."""

    __slots__ = ("req", "row", "first", "token", "pos", "write", "done",
                 "budget", "collected", "temp", "top_k", "eos", "has_eos",
                 "_cache", "_rng_src", "_pad_row")

    def __init__(self, req, row, *, token, pos, write, done, budget):
        self.req = req
        self.row = row
        self.first = token            # the prefill-sampled first token
        self.token = token            # model input for the next step
        self.pos = pos
        self.write = write
        self.done = done
        self.budget = budget          # decode tokens still owed (n - 1)
        self.collected: List[int] = []
        self.temp = req.temperature
        self.top_k = req.top_k or 0
        self.eos = req.eos_token if req.eos_token is not None else 0
        self.has_eos = req.eos_token is not None


class _Inflight:
    """One dispatched-but-unharvested quantum: the device output handles
    (futures under async dispatch — touching them does NOT block) plus a
    snapshot of the lanes that were live at dispatch.  The harvest
    collects tokens against the SNAPSHOT, and only for lanes whose
    occupant is still the same slot object — a lane evicted (and
    possibly re-filled) between dispatch and harvest contributes zombie
    tokens that must be discarded, exactly as the synchronous loop never
    would have stepped it."""

    __slots__ = ("toks", "dones", "snapshot", "quantum")

    def __init__(self, toks, dones, snapshot, quantum):
        self.toks = toks
        self.dones = dones
        self.snapshot = snapshot
        self.quantum = quantum


class DecodeScheduler:
    """The continuous-batching engine: one background thread owns the
    device (prefills at admission, one compiled pool step for decode);
    request threads ``submit()`` and block on the returned
    ``PendingRequest``.

    Knobs (constructor arg, falling back to env):
      slots     KFT_SERVE_SLOTS            pool width (default 8)
      slot_len  KFT_SERVE_SLOT_LEN         cache positions per slot
                                           (default model max_seq_len)
      quantum   KFT_SERVE_DECODE_QUANTUM   decode steps per dispatch /
                                           admission check (default 8)

    A crash in the loop fails every outstanding request with the error
    and marks the scheduler dead (``alive`` False) — the serving layer
    falls back to the lock-serialized path instead of hanging clients.
    """

    def __init__(self, model, params, *, slots: Optional[int] = None,
                 slot_len: Optional[int] = None,
                 quantum: Optional[int] = None,
                 mesh=None,
                 pipeline: Optional[bool] = None,
                 telemetry: Optional[Callable[[], object]] = None):
        self.model = model
        self.params = params
        self.slots = slots or config.env_int("KFT_SERVE_SLOTS", 8)
        self.slot_len = slot_len or config.env_int(
            "KFT_SERVE_SLOT_LEN", 0) or model.cfg.max_seq_len
        if self.slot_len > model.cfg.max_seq_len:
            raise ValueError(
                f"slot_len {self.slot_len} exceeds the model's "
                f"max_seq_len {model.cfg.max_seq_len}"
            )
        self.quantum = quantum or config.env_int(
            "KFT_SERVE_DECODE_QUANTUM", 8)
        self.mesh = mesh
        # Pipelined dispatch (module docstring): overlap host bookkeeping
        # with device execution.  KFT_SERVE_PIPELINE=0 pins the
        # synchronous loop (the bench A/B arm and a rollback lever).
        self.pipeline = pipeline if pipeline is not None else \
            config.env_bool("KFT_SERVE_PIPELINE", True)
        # Zero-arg callable so a service can re-attach telemetry (every
        # create_app builds a fresh registry) without a stale reference
        # pinning dead instruments.
        self._telemetry = telemetry or (lambda: None)

        self._cond = threading.Condition()
        self._queue: List[PendingRequest] = []
        self._pending_rows: List[_Slot] = []  # prefilled, waiting for slots
        self._slot_state: List[Optional[_Slot]] = [None] * self.slots
        self._stop_flag = False
        self._dead: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._admitted_total = 0
        self._evicted_total = 0
        self._steps_total = 0

        # Device state, touched only by the loop thread after start.
        self._cache = None
        self._rngs = None
        self._pad_rows = None
        self._carry = None
        # The one un-harvested quantum (pipelined dispatch); plus the
        # overlap accounting the serve_dispatch_overlap_ratio gauge and
        # the bench A/B read.
        self._inflight: Optional[_Inflight] = None
        self._blocked_s = 0.0
        self._cycle_s = 0.0
        self._t_cycle_mark: Optional[float] = None
        self._batch_ns = None
        if mesh is not None:
            from kubeflow_tpu.parallel.sharding import batch_sharding

            self._batch_ns = batch_sharding(mesh)

    # -- public surface ---------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._dead is None and not self._stop_flag

    def submit(self, rows: List[List[int]], *, max_new_tokens: int,
               temperature: float = 0.0, top_k: Optional[int] = None,
               eos_token: Optional[int] = None, seed: int = 0,
               tokens=None, prompt_mask=None,
               priority: int = DEFAULT_PRIORITY,
               deadline: Optional[float] = None) -> PendingRequest:
        """Queue one request (a list of prompt token rows).  Raises
        ValueError synchronously when prompt+budget cannot fit a slot —
        the same contract as the sequential path's cache-length check.

        ``tokens``/``prompt_mask`` optionally carry the already
        right-padded device arrays (the serving layer validates and pads
        every request anyway — re-padding the rows here would double the
        O(total tokens) prep on the hot path); when absent the scheduler
        pads ``rows`` itself (library use)."""
        longest = max(len(r) for r in rows)
        if longest + max_new_tokens > self.slot_len:
            raise ValueError(
                f"prompt_len ({longest}) + max_new_tokens "
                f"({max_new_tokens}) = {longest + max_new_tokens} exceeds "
                f"the scheduler slot length {self.slot_len}"
            )
        req = PendingRequest(
            rows, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, eos_token=eos_token, seed=seed,
            priority=priority, deadline=deadline)
        req.tokens = tokens
        req.prompt_mask = prompt_mask
        tel = self._telemetry()
        with self._cond:
            # Checked under the lock: a loop crash concurrent with this
            # submit must either fail the request here or see it in the
            # queue when _fail_outstanding drains — never neither (a
            # hung client).
            if self._dead is not None:
                raise RuntimeError(
                    "decode scheduler is dead") from self._dead
            if self._stop_flag:
                raise RuntimeError("decode scheduler is stopped")
            self._queue.append(req)
            if tel is not None:
                tel.queue_depth.inc(len(rows))
            self._cond.notify()
        self.start()
        return req

    def start(self):
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            if self._dead is not None or self._stop_flag:
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="kft-decode-scheduler")
            self._thread.start()

    def stop(self):
        """Stop the loop; outstanding requests fail with RuntimeError."""
        with self._cond:
            self._stop_flag = True
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def stats(self) -> dict:
        with self._cond:
            queued = sum(len(r.rows) for r in self._queue) + len(
                self._pending_rows)
        return {
            "queued_rows": queued,
            "active_rows": sum(
                s is not None for s in self._slot_state),
            "admitted_total": self._admitted_total,
            "evicted_total": self._evicted_total,
            "steps_total": self._steps_total,
            "slots": self.slots,
            "slot_len": self.slot_len,
            "pipeline": self.pipeline,
            "dispatch_blocked_s": round(self._blocked_s, 6),
            "dispatch_cycle_s": round(self._cycle_s, 6),
            "dispatch_overlap_ratio": round(
                1.0 - self._blocked_s / self._cycle_s, 6)
            if self._cycle_s > 0 else 0.0,
        }

    # -- loop thread ------------------------------------------------------

    def _loop(self):
        try:
            self._ensure_pool()
            while True:
                with self._cond:
                    while (not self._stop_flag and not self._queue
                           and not self._pending_rows
                           and self._inflight is None
                           and all(s is None for s in self._slot_state)):
                        self._cond.wait()
                    if self._stop_flag:
                        break
                self._admit()
                if any(s is not None for s in self._slot_state):
                    self._run_quantum()
                else:
                    # Every lane drained at the last harvest while one
                    # more quantum was already in flight: drain it (all
                    # its lanes are zombies by construction) before
                    # sleeping, so its device buffers free.
                    self._harvest()
        except BaseException as exc:  # noqa: BLE001 — fail every waiter
            self._dead = exc
            self._fail_outstanding(exc)
            return
        self._fail_outstanding(RuntimeError("scheduler stopped"))

    def _ensure_pool(self):
        if self._cache is not None:
            return
        self._cache = _init_pool(
            self.model, self.params, slots=self.slots,
            slot_len=self.slot_len)
        self._rngs = jax.random.split(jax.random.key(0), self.slots)
        self._pad_rows = jnp.full(
            (self.slots, self.slot_len), _NEG_INF, jnp.float32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from kubeflow_tpu.parallel.sharding import data_axes

            axes = data_axes(self.mesh)
            if axes:
                def place(x):
                    spec = [None] * x.ndim
                    spec[max(x.ndim - 4, 0)] = axes
                    return jax.device_put(
                        x, NamedSharding(self.mesh, P(*spec)))

                self._cache = jax.tree.map(
                    lambda x: place(x) if getattr(x, "ndim", 0) >= 4
                    else x, self._cache)
                self._pad_rows = jax.device_put(
                    self._pad_rows, self._batch_ns)

    def _free_slots(self) -> List[int]:
        return [i for i, s in enumerate(self._slot_state) if s is None]

    def _next_queued(self, *, pop: bool) -> Optional[PendingRequest]:
        """Admission-order selection under the queue lock: first fail
        queued requests whose deadline already expired (a dead request
        must never reach prefill — its client stopped waiting), then
        pick the best (lowest) priority class, FIFO within a class.
        ``pop`` removes the pick; the paged scheduler peeks instead —
        chunked prefill keeps the request queued until
        ``_begin_prefill`` owns it."""
        now = time.monotonic()
        with self._cond:
            expired = [r for r in self._queue
                       if r.deadline is not None and now >= r.deadline]
            if expired:
                self._queue = [r for r in self._queue
                               if r not in expired]
            req = None
            if self._queue:
                i = min(range(len(self._queue)),
                        key=lambda j: self._queue[j].priority)
                req = self._queue.pop(i) if pop else self._queue[i]
        tel = self._telemetry()
        for dead in expired:
            dead._fail(DeadlineExceeded(
                "request deadline expired while queued "
                f"({now - dead.deadline:.3f}s past cutoff)"))
            if tel is not None:
                tel.queue_depth.dec(len(dead.rows))
        return req

    def _admit(self):
        """Fill free slots: first from prefilled pending rows, then by
        prefilling queued requests (priority classes, FIFO within a
        class — see ``_next_queued``).

        Crash safety: rows live in ``_pending_rows`` (or still in the
        queue) at every point a device call can raise — peeked, placed,
        THEN popped — so ``_fail_outstanding`` can always reach their
        requests; a row held only in a local variable would hang its
        client forever."""
        while True:
            free = self._free_slots()
            while free and self._pending_rows:
                self._place(self._pending_rows[0], free.pop(0))
                self._pending_rows.pop(0)
            if not free or self._pending_rows:
                return
            req = self._next_queued(pop=True)
            if req is None:
                return
            try:
                self._pending_rows.extend(self._prefill(req))
            except BaseException as exc:  # noqa: BLE001 — per-request
                req._fail(exc)
                tel = self._telemetry()
                if tel is not None:
                    tel.queue_depth.dec(len(req.rows))

    def _prefill(self, req: PendingRequest) -> List[_Slot]:
        """Admission prefill: EXACTLY the sequential request-batched
        prompt pass (same ``generate_prefill`` jit the lock path uses,
        same shapes — compile caches are shared), then peel the carry
        into per-row slot states.  Rows already complete (budget 1, or
        EOS on the first token) finish here without touching a slot."""
        from kubeflow_tpu.models.generate import generate_prefill

        rows = req.rows
        if req.tokens is not None:
            prompt, mask = req.tokens, req.prompt_mask
        else:
            longest = max(len(r) for r in rows)
            prompt = jnp.array(
                [r + [0] * (longest - len(r)) for r in rows], jnp.int32)
            mask = jnp.array(
                [[1] * len(r) + [0] * (longest - len(r)) for r in rows],
                bool)
        n = req.max_new_tokens
        req.t_admitted = time.perf_counter()
        req.admitted.set()
        first, ((carry, pad_bias), _budget) = generate_prefill(
            self.model, self.params, prompt, prompt_mask=mask,
            rng=jax.random.key(req.seed), max_new_tokens=n,
            temperature=req.temperature, top_k=req.top_k,
            eos_token=req.eos_token,
        )
        cache, first_d, lengths, row_rngs, done0 = carry
        first_h, lengths_h, done_h = jax.device_get(
            (first_d, lengths, done0))
        req.t_first = time.perf_counter()
        req.first_token.set()
        cache_len_req = pad_bias.shape[-1]
        # Slot bias rows: the request's prompt-padding bias, extended
        # with zeros to slot_len (the per-row causal mask hides the
        # tail until it is really written).
        pads = jnp.zeros(
            (len(rows), self.slot_len), jnp.float32
        ).at[:, :cache_len_req].set(pad_bias[:, 0, 0, :])

        tel = self._telemetry()
        out = []
        eos = req.eos_token
        for i in range(len(rows)):
            tok0 = int(first_h[i])
            if n == 1 or bool(done_h[i]):
                # Complete at admission, no slot needed.  Counted
                # admitted AND evicted here so the balance invariant
                # (admitted == evicted + slots_active) holds at every
                # instant; sequential semantics right-pad with EOS.
                self._admitted_total += 1
                self._evicted_total += 1
                if tel is not None:
                    tel.queue_depth.dec(1)
                    tel.scheduler_admitted.inc()
                    tel.scheduler_evicted.inc()
                self._complete_row(req, i, [tok0] + [eos] * (n - 1))
                continue
            slot = _Slot(
                req, i, token=tok0, pos=int(lengths_h[i]),
                write=int(prompt.shape[1]), done=False, budget=n - 1)
            slot._cache = cache          # request cache, sliced at place
            slot._rng_src = (row_rngs, i)
            slot._pad_row = pads[i]
            out.append(slot)
        return out

    def _place(self, slot: _Slot, idx: int):
        """Insert a prefilled row into pool slot ``idx``.  Admission is
        counted HERE — a prefilled row waiting in the pending-insert
        list still reads as queued (serve_queue_depth's 'not yet holding
        a decode slot' contract), and admitted == evicted + slots_active
        holds at every instant."""
        row_rngs, i = slot._rng_src
        self._cache, self._rngs, self._pad_rows = _place_row(
            self._cache, self._rngs, self._pad_rows,
            slot._cache, row_rngs, slot._pad_row,
            jnp.int32(idx), jnp.int32(i))
        self._admitted_total += 1
        tel = self._telemetry()
        if tel is not None:
            tel.queue_depth.dec(1)
            tel.scheduler_admitted.inc()
            tel.slots_active.set(
                1 + sum(s is not None for s in self._slot_state))
        # Drop the device references so an evicted request's prefill
        # cache can free once its last pending row is placed.
        del slot._cache, slot._rng_src, slot._pad_row
        self._slot_state[idx] = slot
        # The device carry no longer reflects the pool: rebuild it from
        # the slot bookkeeping at the next quantum.
        self._carry = None

    def _run_quantum(self):
        """One decode quantum, pipelined: dispatch quantum N+1 from the
        device-resident carry FIRST, then harvest quantum N's tokens —
        the host-side collection/eviction work overlaps N+1's device
        execution instead of serializing with it.  At most one quantum
        is ever un-harvested.  ``pipeline=False`` harvests its own
        dispatch immediately (the synchronous loop, token-identical by
        the snapshot discipline — see ``_Inflight``)."""
        if self._pre_dispatch_sync():
            return
        prev = self._inflight
        if prev is not None and self._inflight_ready(prev):
            # Opportunistic harvest: quantum N's tokens are ALREADY
            # host-visible, so harvesting first costs no wait and gets
            # its evictions (and any admission they unblock) into
            # quantum N+1 instead of burning a zombie quantum on rows
            # that finished.  On a genuinely async device the tokens
            # are still in flight here and the dispatch keeps its head
            # start — this fast path only fires when the pipeline has
            # nothing left to hide.
            self._inflight = None
            self._harvest_handle(prev)
            prev = None
            self._admit()
            if self._pre_dispatch_sync():
                return
        self._inflight = self._dispatch_quantum()
        if prev is not None:
            self._harvest_handle(prev)
        if not self.pipeline:
            self._harvest()

    @staticmethod
    def _inflight_ready(h: _Inflight) -> bool:
        """Whether a dispatched quantum's results are already on host —
        a committed-transfer check, never a wait."""
        try:
            return h.toks.is_ready() and h.dones.is_ready()
        except AttributeError:  # pragma: no cover — older jax.Array
            return False

    def _pre_dispatch_sync(self) -> bool:
        """Pipeline sync point: a cleared carry means an admission
        changed the pool, and its rebuild reads host pointers
        (token/pos/write) that only the pending harvest can update — so
        harvest BEFORE rebuilding.  Returns True when the harvest's
        evictions leave nothing to dispatch."""
        if self._carry is None:
            self._harvest()
        return not any(s is not None for s in self._slot_state)

    def _dispatch_quantum(self) -> _Inflight:
        """Launch one compiled multi-step dispatch over the pool and
        return the un-harvested handle (async dispatch: this does not
        block on the results).

        The device-side carry (token/pos/write/done + the per-row
        sampling arrays) round-trips between quanta WITHOUT touching the
        host: it is rebuilt from the slot bookkeeping only when an
        admission changed the pool (``_place`` clears it).  Evictions
        deliberately do NOT invalidate it — a vacated slot keeps
        stepping as a zombie whose writes stay clamped inside its own
        (masked) region and whose tokens the harvest discards; the next
        occupant overwrites everything that matters at placement."""
        state = self._slot_state
        if self._carry is None:
            def dev(vals, dtype):
                arr = jnp.asarray(vals, dtype)
                if self._batch_ns is not None:
                    arr = jax.device_put(arr, self._batch_ns)
                return arr

            temps = [s.temp if s else 0.0 for s in state]
            self._carry = (
                dev([s.token if s else 0 for s in state], jnp.int32),
                dev([s.pos if s else 0 for s in state], jnp.int32),
                dev([s.write if s else 0 for s in state], jnp.int32),
                dev([s.done if s else True for s in state], bool),
                dev(temps, jnp.float32),
                dev([s.top_k if s else 0 for s in state], jnp.int32),
                dev([s.eos if s else 0 for s in state], jnp.int32),
                dev([s.has_eos if s else False for s in state], bool),
                any(t != 0.0 for t in temps),
            )
        (token, pos, write, done, temps_d, top_ks_d, eos_d, has_eos_d,
         sampled) = self._carry
        (self._cache, token, pos, write, self._rngs, done, toks,
         dones) = _pool_steps(
            self.model, self._cache, self.params,
            token, pos, write, self._rngs, done,
            self._pad_rows, temps_d, top_ks_d, eos_d, has_eos_d,
            quantum=self.quantum, sampled=sampled,
        )
        self._carry = (token, pos, write, done, temps_d, top_ks_d, eos_d,
                       has_eos_d, sampled)
        if self._t_cycle_mark is None:
            self._t_cycle_mark = time.perf_counter()
        return _Inflight(toks, dones, list(state), self.quantum)

    def _harvest(self):
        if self._inflight is not None:
            handle, self._inflight = self._inflight, None
            self._harvest_handle(handle)

    def _harvest_handle(self, h: _Inflight):
        """Block on one dispatched quantum's tokens, then run the host
        bookkeeping: token collection, EOS/budget eviction, overlap
        accounting.  Collection goes by the dispatch-time snapshot and
        skips any lane whose occupant changed since (see ``_Inflight``)."""
        t0 = time.perf_counter()
        toks_h, dones_h = jax.device_get((h.toks, h.dones))
        t1 = time.perf_counter()
        # Overlap ratio: the fraction of each dispatch→harvest cycle the
        # host was NOT blocked in device_get.  The synchronous loop runs
        # the whole quantum inside that wait; pipelining moves the wait
        # behind the bookkeeping of the previous quantum.
        self._blocked_s += t1 - t0
        self._cycle_s += t1 - self._t_cycle_mark
        self._t_cycle_mark = t1
        self._steps_total += h.quantum
        tel = self._telemetry()
        active = sum(s is not None for s in h.snapshot)
        if tel is not None:
            tel.batch_fill_ratio.observe(active / max(self.slots, 1))
            tel.slots_active.set(
                sum(s is not None for s in self._slot_state))
            if self._cycle_s > 0 and hasattr(tel, "dispatch_overlap"):
                tel.dispatch_overlap.set(
                    1.0 - self._blocked_s / self._cycle_s)
        for i, slot in enumerate(h.snapshot):
            if slot is None or self._slot_state[i] is not slot:
                continue
            for t in range(h.quantum):
                if len(slot.collected) >= slot.budget:
                    break
                slot.collected.append(int(toks_h[t, i]))
                slot.done = bool(dones_h[t, i])
            slot.token = int(toks_h[h.quantum - 1, i])
            slot.pos += h.quantum
            slot.write += h.quantum
            if slot.done or len(slot.collected) >= slot.budget:
                self._evict(i)

    def _evict(self, idx: int):
        slot = self._slot_state[idx]
        self._slot_state[idx] = None
        # Output rows are first-token + decode tokens, EOS-padded to the
        # budget — exactly the sequential path's post-EOS right-padding.
        fill = slot.req.eos_token
        out = slot.collected + [fill] * (slot.budget - len(slot.collected))
        self._complete_row(slot.req, slot.row, [slot.first] + out)
        self._evicted_total += 1
        tel = self._telemetry()
        if tel is not None:
            tel.scheduler_evicted.inc()
            tel.slots_active.set(
                sum(s is not None for s in self._slot_state))

    def _complete_row(self, req: PendingRequest, row: int, tokens: list):
        req.outputs[row] = tokens
        req.remaining -= 1
        if req.remaining == 0:
            req.t_done = time.perf_counter()
            req.done.set()

    def _fail_outstanding(self, exc: BaseException):
        # Drop the un-harvested quantum, if any: its snapshot slots are
        # failed below, and a dead/stopped scheduler must not block on
        # device results nobody will read.
        self._inflight = None
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
            pending = list(self._pending_rows)
            self._pending_rows.clear()
        tel = self._telemetry()
        for req in queued:
            if tel is not None:
                tel.queue_depth.dec(len(req.rows))
            req._fail(exc)
        # Pending-insert rows were never admitted (placement-time
        # accounting), so they only drain the queue gauge; in-flight
        # slot rows WERE admitted — count them evicted so
        # admitted == evicted + slots_active stays true after a crash
        # (the service keeps serving on the lock path and operators
        # alert on that balance).  A row that crashed between _place and
        # its pending-list pop is in both sets — count it once, as
        # placed.
        placed = {id(s) for s in self._slot_state if s}
        pending = [s for s in pending if id(s) not in placed]
        if tel is not None and pending:
            tel.queue_depth.dec(len(pending))
        seen = set()
        for slot in pending + [s for s in self._slot_state if s]:
            if id(slot.req) not in seen:
                seen.add(id(slot.req))
                slot.req._fail(exc)
        in_flight = sum(s is not None for s in self._slot_state)
        self._evicted_total += in_flight
        self._slot_state = [None] * self.slots
        if tel is not None:
            if in_flight:
                tel.scheduler_evicted.inc(in_flight)
            tel.slots_active.set(0)
