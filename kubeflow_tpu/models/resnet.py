"""ResNet v1.5 in Flax, TPU-first.

This is the headline-benchmark model (BASELINE.json: "in-notebook ResNet50
images/sec/chip"; reference config 2 "jupyter-tensorflow-full single-device
notebook (ResNet50 CIFAR)").  The reference platform has no model code at all
(SURVEY.md §2.13) — it ships ResNet inside TF/CUDA notebook images
(reference ``components/example-notebook-servers/jupyter-tensorflow/``).

TPU-first choices:
* bfloat16 compute / float32 params and batch stats — keeps the convolutions
  on the MXU at full rate without loss-scale bookkeeping.
* NHWC layout (XLA:TPU's native conv layout).
* v1.5 downsampling (stride on the 3x3, not the 1x1) — better accuracy at
  equal FLOPs, and identical MXU utilisation.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.registry import register_model

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last norm's scale so each block starts as identity:
        # standard large-batch trick; costs nothing on TPU.
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: [N,H,W,C] -> [N,H/b,W/b,C*b*b] (pure reshape /
    transpose — free on TPU, it's a layout change)."""
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(
            f"space_to_depth needs H and W divisible by {block}, got "
            f"{h}x{w}"
        )
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, c * block * block)


class ResNet(nn.Module):
    """ResNet v1.5.  ``stem='cifar'`` swaps the 7x7/maxpool stem for a 3x3;
    ``stem='space_to_depth'`` is the MLPerf conv0 rewrite — input 2x2
    space-to-depth (3->12 channels) + a 4x4 stride-1 conv over the 112x112
    s2d grid (the 7x7/s2's receptive field, zero-padded to 8x8, folded into
    4x4x12), keeping the 3x3/s2 maxpool.  Output shapes and layer count
    match the classic stem exactly; the win is purely that a 3-channel conv
    wastes the MXU's 128-wide channel lanes on padding while 12 channels
    over a quarter of the positions packs them 4x better."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    stem: str = "imagenet"  # "imagenet" | "space_to_depth" | "cifar"

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        act = nn.relu

        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                     name="conv_init")(x)
            x = norm(name="norm_init")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        elif self.stem == "space_to_depth":
            x = space_to_depth(x, 2)  # [N,112,112,12] for 224 input
            # Stride 1: stride 2 in pixel space is absorbed by the s2d
            # block; output [N,112,112,64], identical to the 7x7/s2 path.
            x = conv(self.num_filters, (4, 4), (1, 1), padding="SAME",
                     name="conv_init")(x)
            x = norm(name="norm_init")(x)
            x = act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        elif self.stem == "cifar":
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
            x = norm(name="norm_init")(x)
            x = act(x)
        else:
            raise ValueError(
                f"unknown stem {self.stem!r}; expected 'imagenet', "
                "'space_to_depth', or 'cifar'"
            )

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


_CONFIGS = {
    "resnet18": dict(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock),
    "resnet34": dict(stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock),
    "resnet50": dict(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock),
    "resnet101": dict(stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock),
    "resnet152": dict(stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock),
}


def _make(name):
    cfg = _CONFIGS[name]

    @register_model(name)
    def factory(**kwargs):
        return ResNet(**{**cfg, **kwargs})

    factory.__name__ = name
    return factory


for _name in _CONFIGS:
    _make(_name)


# Small net for unit tests: 2 stages, runs in milliseconds on CPU.
@register_model("resnet_tiny")
def resnet_tiny(**kwargs):
    defaults = dict(
        stage_sizes=[1, 1],
        block_cls=BasicBlock,
        num_filters=8,
        num_classes=10,
        stem="cifar",
        dtype=jnp.float32,
    )
    return ResNet(**{**defaults, **kwargs})
