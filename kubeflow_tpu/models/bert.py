"""BERT encoder in Flax — BASELINE.json config 3
("jupyter-pytorch-full -> PyTorch/XLA notebook, BERT-base fine-tune").

The TPU rebuild's notebook images carry the JAX stack as the first-class
path, so the BERT fine-tune config is served natively by this module (a
PyTorch/XLA image recipe still exists for parity — see
kubeflow_tpu/platform/images/).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.layers import Attention, Embed, Mlp
from kubeflow_tpu.models.registry import register_model


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_classes: int = 2  # sequence-classification head (fine-tune config)
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0


CONFIGS = {
    "bert_debug": BertConfig(vocab_size=128, dim=32, n_layers=2, n_heads=2,
                             mlp_dim=64, max_seq_len=64, dtype=jnp.float32),
    "bert_base": BertConfig(),
    "bert_large": BertConfig(dim=1024, n_layers=24, n_heads=16, mlp_dim=4096),
}


class BertEncoderBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, *, mask_bias, train: bool):
        cfg = self.cfg
        h = Attention(num_heads=cfg.n_heads, dtype=cfg.dtype, name="attn")(
            x, mask_bias=mask_bias
        )
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        x = nn.LayerNorm(dtype=cfg.dtype, name="norm1")(x + h)
        h = Mlp(hidden_dim=cfg.mlp_dim, dtype=cfg.dtype, name="mlp")(x)
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return nn.LayerNorm(dtype=cfg.dtype, name="norm2")(x + h)


class Bert(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(
        self,
        tokens,
        *,
        attention_mask: Optional[jnp.ndarray] = None,
        token_type_ids: Optional[jnp.ndarray] = None,
        train: bool = True,
    ):
        cfg = self.cfg
        b, s = tokens.shape
        x = Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype, name="tok_embed")(tokens)
        pos = Embed(cfg.max_seq_len, cfg.dim, dtype=cfg.dtype, name="pos_embed")(
            jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        )
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(tokens)
        typ = Embed(
            cfg.type_vocab_size, cfg.dim, dtype=cfg.dtype, name="type_embed"
        )(token_type_ids)
        x = nn.LayerNorm(dtype=cfg.dtype, name="embed_norm")(x + pos + typ)

        mask_bias = None
        if attention_mask is not None:
            # [b, s] {0,1} -> additive [b, 1, 1, s] bias over key positions.
            mask_bias = (1.0 - attention_mask[:, None, None, :]) * -1e30
        for i in range(cfg.n_layers):
            x = BertEncoderBlock(cfg, name=f"layer_{i}")(
                x, mask_bias=mask_bias, train=train
            )
        pooled = nn.tanh(
            nn.Dense(cfg.dim, dtype=jnp.float32, name="pooler")(x[:, 0])
        )
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32, name="classifier")(pooled)
        return logits


def _factory(name):
    @register_model(name)
    def make(**overrides):
        return Bert(dataclasses.replace(CONFIGS[name], **overrides))

    make.__name__ = name
    return make


for _n in CONFIGS:
    _factory(_n)
