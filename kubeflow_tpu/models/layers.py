"""Shared transformer building blocks (Flax linen), routed through
``kubeflow_tpu.ops`` so every model picks up the Pallas kernels."""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp

from kubeflow_tpu import ops


@flax.struct.dataclass
class PagedSlots:
    """Per-row paged-KV addressing for ``Attention._update_cache``.

    The paged pool (models/paged.py) stores every row's K/V in one flat
    pooled tensor of ``pool_positions`` slots (``num_pages × page_len``);
    a row's logical cache positions map to physical slots through its
    page table.  The caller resolves that mapping to FLAT indices:

      write  [b, s] int32 — physical slot for each incoming token
      read   [b, L] int32 — physical slot for each of the row's L
                            logical positions (unallocated logical pages
                            point at the reserved null page, which the
                            caller's mask_bias hides)

    ``pool_positions`` is static metadata (the pooled tensors' leading
    dim), so one compiled graph serves one pool geometry.

    ``pool_sharding`` (optional ``jax.sharding.NamedSharding`` over the
    rank-3 pool leaf, static like ``pool_positions``) pins the scatter
    output back onto the pool's at-rest layout under GSPMD: without the
    constraint the partitioner may materialize the post-scatter pool
    replicated, silently un-sharding the cache between decode steps."""

    write: jax.Array
    read: jax.Array
    pool_positions: int = flax.struct.field(pytree_node=False, default=0)
    pool_sharding: Any = flax.struct.field(pytree_node=False, default=None)


class Embed(nn.Module):
    """Token embedding with a use-site replication constraint.

    The table is sharded at rest by the partition rules (vocab→tp,
    dim→fsdp); constraining it replicated at the lookup makes XLA
    all-gather the shards first (the ZeRO-3 use-site gather), so the
    gather's output inherits the batch layout from the token indices.
    Without this the output inherits the table's feature split, which the
    SPMD partitioner can only reconcile with the batch layout through an
    involuntary full rematerialization (replicate + repartition).

    Drop-in for ``nn.Embed`` (same param name/init, no ``attend``).
    """

    num_embeddings: int
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens):
        table = self.param(
            "embedding",
            jax.nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0
            ),
            (self.num_embeddings, self.features),
        )
        from kubeflow_tpu.parallel.sharding import replicate_for_use

        table = replicate_for_use(table.astype(self.dtype))
        return jnp.take(table, tokens, axis=0)


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(), (x.shape[-1],))
        return ops.rms_norm(x, scale, eps=self.eps)


def apply_rope(x: jax.Array, positions: jax.Array, *, theta: float = 10000.0):
    """Rotary embeddings, BSHD input, pairing (x[..., :d/2], x[..., d/2:])."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class Attention(nn.Module):
    """Multi-head / grouped-query attention over ops.dot_product_attention."""

    num_heads: int
    num_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    rope: bool = False
    rope_theta: float = 10000.0
    causal: bool = False
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    # None -> 1/sqrt(head_dim); T5 passes 1.0 (scale folded into init).
    softmax_scale: Optional[float] = None

    @nn.compact
    def __call__(self, x, *, kv=None, positions=None, segment_ids=None,
                 mask_bias=None, decode=False, max_decode_len=None,
                 cache_slots=None):
        """``kv`` switches to cross-attention: keys/values project from the
        encoder sequence instead of ``x`` (RoPE/cache apply to
        self-attention only).

        ``cache_slots`` ([b] int32, single-token decode only) writes each
        row's k/v at its OWN cache slot instead of the shared scalar
        cache index — the continuous-batching slot pool, where rows sit
        at different depths of their generations.  In that mode the
        built-in causal bias is skipped entirely: ``mask_bias`` must
        carry the full per-row visibility mask.

        ``cache_slots`` may also be a ``PagedSlots``: the block-paged
        pool (models/paged.py), where K/V live in ONE flat pooled tensor
        and per-row page tables resolve logical positions to physical
        slots.  Multi-token calls are allowed there (chunked prefill /
        speculative verify); the mask_bias contract is the same."""
        b, s, dim = x.shape
        kv_heads = self.num_kv_heads or self.num_heads
        head_dim = self.head_dim or dim // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, use_bias=False, dtype=self.dtype, name=name
        )
        q = dense((self.num_heads, head_dim), "q_proj")(x)
        k_proj = dense((kv_heads, head_dim), "k_proj")
        v_proj = dense((kv_heads, head_dim), "v_proj")
        if decode and kv is not None:
            # Cross-attention under decode: the source is static for the
            # whole generation, so project K/V ONCE (first call initializes
            # the cache variables; scan steps reuse them — without this,
            # every generated token re-projects the full encoder output in
            # every layer).
            ck = self.variable("cache", "cached_cross_key", lambda: k_proj(kv))
            cv = self.variable("cache", "cached_cross_value", lambda: v_proj(kv))
            k, v = ck.value, cv.value
        else:
            src = x if kv is None else kv
            k = k_proj(src)
            v = v_proj(src)
        if self.rope and kv is None:
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
            q = apply_rope(q, positions, theta=self.rope_theta)
            k = apply_rope(k, positions, theta=self.rope_theta)
        if decode and kv is not None:
            # Cross-attention with the once-projected K/V: no positional
            # masking (every source token is visible modulo mask_bias).
            # Honors attn_impl — the bias-free case (no encoder padding /
            # relative bias) is a flash-eligible cross-length shape
            # (sq = decode tokens, sk = source len).  The bias path stays
            # XLA for now: the kernel carries no bias tiles (a per-tile
            # additive load is future work) and T5's relative/padding
            # bias always lands here.  A FORCED "pallas" softens to
            # "auto" on this opportunistic route: single-token decode
            # steps (sq=1) sit below the kernel's tile floor, and a
            # config that generated fine before must fall back, not
            # raise, when this path's shapes reject the kernel.
            impl = self.attn_impl if mask_bias is None else "xla"
            out = ops.dot_product_attention(
                q, k, v, causal=False, bias=mask_bias,
                impl="auto" if impl == "pallas" else impl,
                softmax_scale=self.softmax_scale,
            )
        elif decode:
            if segment_ids is not None:
                raise ValueError(
                    "decode=True does not support packed sequences "
                    "(segment_ids); the cache is one sequence per batch row"
                )
            k, v, bias = self._update_cache(k, v, max_decode_len,
                                            slots=cache_slots)
            if bias is None:
                # Per-row slot writes: visibility is entirely the
                # caller's mask_bias (scheduler pool step).  Without
                # one, every stale/unwritten cache position would
                # attend unmasked — silently wrong logits, so refuse.
                if mask_bias is None:
                    raise ValueError(
                        "cache_slots decode requires mask_bias: the "
                        "per-row slot path has no built-in causal "
                        "mask, so the caller must supply the full "
                        "visibility bias"
                    )
                bias = mask_bias
            elif mask_bias is not None:
                bias = bias + mask_bias
            out = None
            if s == 1:
                # Single-token decode: a Pallas flash-decode kernel exists
                # (ops/pallas/flash_decode.py) but measured SLOWER than
                # XLA's decode on the current backend (BASELINE.md), so it
                # is opt-in only: KUBEFLOW_TPU_FORCE_FLASH_DECODE=1.
                from kubeflow_tpu.ops.pallas import flash_decode as fd

                # bias must be head-uniform to collapse into a [b, S] row;
                # a per-head bias (ALiBi/T5-style) must take the XLA path.
                if fd.force_enabled() and bias is not None \
                        and bias.shape[1] == 1:
                    rows = jnp.broadcast_to(
                        bias[:, 0, 0, :], (b, k.shape[1])
                    ).astype(jnp.float32)
                    if fd.supported(q, k, v, bias_rows=rows):
                        out = fd.flash_decode(
                            q, k, v, rows, softmax_scale=self.softmax_scale
                        )
            if out is None:
                # Stays impl="xla" deliberately: the cache path ALWAYS has
                # a bias (the unwritten-slot/causal bias from
                # _update_cache), which the flash kernel does not take —
                # and the footprint is [b, h, s, max_len] with s = the
                # prefill chunk, not O(S²) of the full sequence.  The
                # single-token case has the opt-in flash_decode above.
                out = ops.dot_product_attention(
                    q, k, v, causal=False, bias=bias, impl="xla",
                    softmax_scale=self.softmax_scale,
                )
        else:
            out = ops.dot_product_attention(
                q,
                k,
                v,
                causal=self.causal,
                segment_ids=segment_ids,
                bias=mask_bias,
                impl=self.attn_impl,
                softmax_scale=self.softmax_scale,
            )
        out = nn.DenseGeneral(
            dim, axis=(-2, -1), use_bias=False, dtype=self.dtype, name="o_proj"
        )(out)
        return out

    def _update_cache(self, k, v, max_decode_len, slots=None):
        """Autoregressive KV cache (flax "cache" collection): write the new
        k/v at the running index with a static-shape dynamic_update_slice,
        return the full cache plus the mask bias hiding future/unwritten
        slots.  Works for prefill (s>1 at index 0) and single-token decode
        (s=1) under one jit trace each — no data-dependent Python control
        flow (SURVEY-mandated XLA semantics).

        ``slots`` ([b] int32) switches to per-row writes: row i's token
        lands at cache slot ``slots[i]`` via a batched scatter, and the
        returned bias is None — the scalar cache index neither applies
        nor advances, because pool rows progress at independent depths
        (continuous batching, models/scheduler.py).  The caller's
        mask_bias must then carry the complete per-row visibility.

        The cache stays sequence-major ([b, S, kv_h, d]) — XLA's preferred
        decode layout.  A dS-major layout feeding the Pallas flash-decode
        kernel was measured end to end and LOST to XLA on the current
        backend (BASELINE.md decode-kernel log), so the kernel remains an
        opt-in (KUBEFLOW_TPU_FORCE_FLASH_DECODE=1) and the storage serves
        the default path."""
        b, s, kv_heads, head_dim = k.shape
        if max_decode_len is None:
            raise ValueError("decode=True requires max_decode_len")
        if isinstance(slots, PagedSlots):
            # Block-paged pool: K/V for EVERY row live in one flat
            # [pool_positions, kv_h, d] tensor — a row's footprint is the
            # pages its table maps, not a longest-bucket slot.  The
            # classic per-batch cache variables are deliberately NOT
            # created on this path (they would allocate the full
            # fixed-slot pool the paged design exists to avoid).
            # Scatter collisions only happen on the reserved null page
            # (masked trash), so last-writer-wins is harmless.
            pool = slots.pool_positions
            paged_k = self.variable(
                "cache", "paged_key",
                lambda: jnp.zeros((pool, kv_heads, head_dim), k.dtype),
            )
            paged_v = self.variable(
                "cache", "paged_value",
                lambda: jnp.zeros((pool, kv_heads, head_dim), v.dtype),
            )
            k_pool = paged_k.value.at[slots.write].set(k)
            v_pool = paged_v.value.at[slots.write].set(v)
            if slots.pool_sharding is not None:
                k_pool = jax.lax.with_sharding_constraint(
                    k_pool, slots.pool_sharding)
                v_pool = jax.lax.with_sharding_constraint(
                    v_pool, slots.pool_sharding)
            paged_k.value = k_pool
            paged_v.value = v_pool
            # Gather preserves logical order, so a row's [L] view is
            # byte-for-byte the contiguous layout the sequential decode
            # would have used; unallocated logical pages read the null
            # page, which the caller's mask_bias turns into exact-zero
            # attention contributions.
            return k_pool[slots.read], v_pool[slots.read], None
        cached_k = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((b, max_decode_len, kv_heads, head_dim), k.dtype),
        )
        cached_v = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, max_decode_len, kv_heads, head_dim), v.dtype),
        )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if slots is not None:
            if s != 1:
                raise ValueError(
                    f"per-row cache_slots require single-token decode, "
                    f"got s={s}"
                )
            rows = jnp.arange(b)
            k_all = cached_k.value.at[rows, slots].set(k[:, 0])
            v_all = cached_v.value.at[rows, slots].set(v[:, 0])
            cached_k.value = k_all
            cached_v.value = v_all
            return k_all, v_all, None
        idx = cache_index.value
        k_all = jax.lax.dynamic_update_slice(cached_k.value, k, (0, idx, 0, 0))
        v_all = jax.lax.dynamic_update_slice(cached_v.value, v, (0, idx, 0, 0))
        cached_k.value = k_all
        cached_v.value = v_all
        cache_index.value = idx + s
        # Query at global position idx+i sees keys at positions <= idx+i.
        q_pos = idx + jnp.arange(s)
        k_pos = jnp.arange(max_decode_len)
        allowed = k_pos[None, :] <= q_pos[:, None]            # [s, max_len]
        bias = jnp.where(allowed, 0.0, -1e30)[None, None]      # [1,1,s,max_len]
        return k_all, v_all, bias


class SwiGLU(nn.Module):
    hidden_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        gate = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype, name="gate_proj")(x)
        up = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype, name="up_proj")(x)
        y = nn.silu(gate) * up
        return nn.Dense(dim, use_bias=False, dtype=self.dtype, name="down_proj")(y)


class Mlp(nn.Module):
    """Classic GELU MLP (ViT/BERT)."""

    hidden_dim: int
    dtype: Any = jnp.bfloat16
    act: Callable = nn.gelu

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        y = nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc1")(x)
        y = self.act(y)
        return nn.Dense(dim, dtype=self.dtype, name="fc2")(y)
