"""Vision Transformer (ViT) in Flax — BASELINE.json config 4
("codeserver-python image with JAX + Flax, ViT-B/16 training")."""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.layers import Attention, Mlp
from kubeflow_tpu.models.registry import register_model


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0


CONFIGS = {
    "vit_debug": ViTConfig(image_size=32, patch_size=8, dim=32, n_layers=2,
                           n_heads=2, mlp_dim=64, num_classes=10,
                           dtype=jnp.float32),
    "vit_s16": ViTConfig(dim=384, n_layers=12, n_heads=6, mlp_dim=1536),
    "vit_b16": ViTConfig(),
    "vit_l16": ViTConfig(dim=1024, n_layers=24, n_heads=16, mlp_dim=4096),
}


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm1")(x)
        h = Attention(num_heads=cfg.n_heads, dtype=cfg.dtype, name="attn")(h)
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype, name="norm2")(x)
        h = Mlp(hidden_dim=cfg.mlp_dim, dtype=cfg.dtype, name="mlp")(h)
        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return x + h


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, *, train: bool = True):
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(
            cfg.dim,
            (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=cfg.dtype,
            name="patch_embed",
        )(images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.dim)
        cls = self.param(
            "cls_token", nn.initializers.zeros_init(), (1, 1, cfg.dim)
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, cfg.dim)).astype(cfg.dtype), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], cfg.dim),
        )
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, train=train)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


def _factory(name):
    @register_model(name)
    def make(**overrides):
        return ViT(dataclasses.replace(CONFIGS[name], **overrides))

    make.__name__ = name
    return make


for _n in CONFIGS:
    _factory(_n)
