"""Model families shipped in the platform's notebook images.

The reference platform ships model-less CUDA images and leaves all modelling
to user notebooks (see SURVEY.md §2.10/§2.13; reference
``components/example-notebook-servers/``).  The TPU rebuild instead bundles a
small, idiomatic JAX model zoo covering the baseline configs in
BASELINE.json: ResNet50 (images/sec/chip headline), ViT-B/16, BERT-base, and
a Llama-style decoder for the multi-host pjit config.
"""

import jax

# Partition-invariant threefry (rationale in models/generate.py).  Set
# HERE — before any create_model()/init() can run — not only at the
# generate/sharding imports: the flag changes jax.random's bit stream,
# so flipping it lazily mid-process (first generate() call) would make
# two same-seed param inits in one process disagree depending on which
# ran before the first lazy import.
jax.config.update("jax_threefry_partitionable", True)

from kubeflow_tpu.models import registry
from kubeflow_tpu.models.registry import create_model, list_models, register_model

__all__ = ["create_model", "list_models", "register_model", "registry"]
