"""Minimal generation server: serve a model zoo decoder over HTTP.

The platform spawns notebooks; a notebook that trained a model serves it
with one command:

    python -m kubeflow_tpu.models.serve --model llama_125m \\
        --checkpoint-dir /workspace/ckpt --port 8080

Endpoints:
  GET  /healthz             liveness
  GET  /readyz              readiness: runs (and caches) a one-token warm
                            generate() — 200 only after the model has
                            actually produced a token, so a controller's
                            rolling update never routes traffic to a
                            replica that would compile-stall or crash on
                            its first request
  GET  /v1/model            model name/config summary
  POST /v1/generate         {"tokens": [[...]], "max_new_tokens": 32,
                             "temperature": 0.8, "top_k": 40, "seed": 0}
                            -> {"tokens": [[...]]}

The handler batches whatever rows arrive in one request, right-pads them
to the longest prompt, and calls the jit generate() path (models/
generate.py) — repeated shapes hit the compile cache.  This is a
single-process server for notebook-scale serving, not a fleet frontend.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.telemetry.serve import ServeTelemetry, span_or_null


def _validate_and_pad(rows, vocab: int, *, max_new_tokens, default_max,
                      limit_new, limit_source, top_k, eos_token,
                      limit_rows: int = 64):
    """Shared request validation + right-padding for both services.
    Returns (tokens [b, longest] int32, mask [b, longest] bool, n).

    Size limits reject BEFORE the O(total tokens) Python scan — an
    oversized request must not cost a 50M-iteration loop to 400."""
    if not rows or not all(isinstance(r, list) and r for r in rows):
        raise ValueError("tokens must be a non-empty list of non-empty rows")
    if limit_rows and len(rows) > limit_rows:
        raise ValueError(
            f"batch of {len(rows)} rows exceeds the service limit {limit_rows}"
        )
    n = default_max if max_new_tokens is None else max_new_tokens
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        raise ValueError(f"max_new_tokens must be a positive int, got {n!r}")
    if limit_new and n > limit_new:
        raise ValueError(
            f"max_new_tokens {n} exceeds the service limit {limit_new}"
        )
    longest = max(len(r) for r in rows)
    if limit_source and longest > limit_source:
        raise ValueError(
            f"input length {longest} exceeds the service limit {limit_source}"
        )
    for r in rows:
        for t in r:
            # bool is an int subclass: JSON true/false must 400, not
            # silently become token 1/0.
            if isinstance(t, bool) or not isinstance(t, int) \
                    or not 0 <= t < vocab:
                raise ValueError(f"token {t!r} outside [0, {vocab})")
    if top_k is not None and (not isinstance(top_k, int)
                              or isinstance(top_k, bool) or top_k < 1):
        raise ValueError(f"top_k must be a positive int, got {top_k!r}")
    if eos_token is not None and (isinstance(eos_token, bool)
                                  or not isinstance(eos_token, int)):
        raise ValueError(f"eos_token must be an int, got {eos_token!r}")
    tokens = jnp.array(
        [r + [0] * (longest - len(r)) for r in rows], jnp.int32
    )
    mask = jnp.array(
        [[1] * len(r) + [0] * (longest - len(r)) for r in rows], bool
    )
    return tokens, mask, n


# "Client did not set eos_token" sentinel: each service resolves it to its
# own default_eos_token, so the generate path and the token metric can never
# disagree about which sentinel ends a row.
_UNSET = object()


def _generated_token_count(rows, eos_token):
    """Tokens produced per row, counting through the first EOS and
    excluding the post-EOS padding generate() right-fills with — the
    throughput metric must not credit padding as generated tokens."""
    if eos_token is None:
        return sum(len(r) for r in rows)
    total = 0
    for r in rows:
        total += r.index(eos_token) + 1 if eos_token in r else len(r)
    return total


def _check_deadline(deadline):
    """Lock-path deadline gate: the request's X-KFT-Deadline-Seconds
    budget ran out while it waited for the service lock — fail it
    before spending device time on a client that already gave up (the
    scheduler path has the same gate at admission selection)."""
    if deadline is not None and time.monotonic() >= deadline:
        from kubeflow_tpu.models.scheduler import DeadlineExceeded

        raise DeadlineExceeded(
            "request deadline expired while queued for the service lock")


def _telemetry_request(service, rows, eos_token, validate, run):
    """ONE request lifecycle for both services — admit (validate, before
    the lock so bad requests 400 without queueing) → queue (lock wait,
    depth-gauged) → run → token counters + trace close.  The scaffolding
    lives here so a telemetry change (span order, queue-depth semantics)
    cannot drift between the decoder-only and seq2seq paths.  With
    ``service.telemetry`` None (library use) every span/instrument is a
    no-op and the lock semantics are exactly the pre-telemetry ones.

    The scheduler path (``GenerationService._generate_scheduled``)
    mirrors this sequence against scheduler events instead of the lock;
    if you change span names/order or counter semantics here, change it
    there too — tests pin both engines to the same span tree and
    counter values (tests/test_serve.py, tests/test_scheduler.py).

    ``validate`` returns the positional args ``run(tel, t_arrival, ...)``
    receives after the admit span; ``run`` executes under the lock and
    returns the row lists handed back to the caller."""
    tel = service.telemetry
    t_arrival = time.perf_counter()
    if tel is not None:
        tel.begin_request()
    try:
        with span_or_null(tel, "admit"):
            args = validate()
            if tel is not None:
                tel.batch_rows.observe(len(rows))
                tel.batch_fill_ratio.observe(
                    len(rows) / max(service.max_batch_rows, 1))
                tel.input_tokens.inc(sum(len(r) for r in rows))
        with span_or_null(tel, "queue"):
            if tel is not None:
                tel.queue_depth.inc()
            try:
                service._lock.acquire()
            finally:
                if tel is not None:
                    tel.queue_depth.dec()
        try:
            result = run(tel, t_arrival, *args)
        finally:
            service._lock.release()
        if tel is not None:
            tel.output_tokens.inc(_generated_token_count(result, eos_token))
            tel.finish_request("ok")
        return result
    except BaseException:
        if tel is not None:
            tel.finish_request("error")
        raise


class GenerationService:
    default_eos_token: Optional[int] = None
    # ServeTelemetry, attached by create_app; None = un-instrumented
    # library use (every telemetry touch is guarded).
    telemetry: Optional[ServeTelemetry] = None

    def __init__(self, model, params, *, default_max_new_tokens: int = 32,
                 max_batch_rows: int = 64, mesh=None,
                 use_scheduler: Optional[bool] = None,
                 draft_model=None, draft_params=None):
        self.model = model
        self.params = params
        self.default_max_new_tokens = default_max_new_tokens
        self.max_batch_rows = max_batch_rows
        # SPMD serving (load_service --mesh): params arrive sharded; the
        # scheduler places its slot pool's batch axis with batch_sharding
        # over the same mesh.
        self.mesh = mesh
        # Speculative decoding (models/paged.py): a small same-vocab
        # draft model proposes tokens the target verifies in one step.
        # Only the paged scheduler consumes it; on every other path the
        # pair is inert.
        self.draft_model = draft_model
        self.draft_params = draft_params
        # Continuous batching (models/scheduler.py): instrumented
        # services route through the cross-request scheduler unless
        # KFT_SERVE_SCHEDULER=0 (or use_scheduler=False) pins the
        # lock-serialized path.  Un-instrumented library use always
        # takes the lock path — no background thread appears behind a
        # plain GenerationService(model, params).generate() call.
        self._use_scheduler = use_scheduler
        self._scheduler = None
        # Structured paged-engine fallback record ({reason, detail}, or
        # None while the paged engine serves) — surfaced by /debug/serve
        # and counted by serve_paged_fallback_total{reason}.  A mesh run
        # no longer falls back silently: the paged pool shards over the
        # page axis (models/paged.py), so only genuinely unsupported
        # combinations land here.
        self.scheduler_fallback = None
        # generate() donates nothing but jit compilation is per-shape; a
        # lock keeps concurrent requests from racing device memory on tiny
        # single-chip deployments.
        self._lock = threading.Lock()

    def _scheduler_or_none(self):
        """The DecodeScheduler to route through, or None for the
        lock-serialized path.  A scheduler that died (loop crash) fails
        over to the lock path instead of hanging clients."""
        if self.telemetry is None:
            return None
        use = self._use_scheduler
        if use is None:
            from kubeflow_tpu.platform import config as _config

            use = _config.env_bool("KFT_SERVE_SCHEDULER", True)
        if not use:
            return None
        with self._lock:
            if self._scheduler is None:
                from kubeflow_tpu.platform import config as _config

                # The paged engine (block-paged KV + prefix reuse +
                # chunked prefill + optional speculative decoding) is
                # the default, mesh or not — under a mesh the pool
                # shards over the page axis (models/paged.py).  The
                # remaining fallbacks are explicit and RECORDED
                # (serve_paged_fallback_total + /debug/serve): a silent
                # drop to the fixed pool cost PR 17's wins exactly on
                # the sharded deployments that serve the most traffic.
                reason = detail = None
                if not _config.env_bool("KFT_SERVE_PAGED", True):
                    reason = "env-disabled"
                    detail = ("KFT_SERVE_PAGED=0 pins the fixed-slot "
                              "pool")
                elif self.mesh is not None \
                        and self.draft_model is not None:
                    reason = "spec-decode-mesh"
                    detail = ("speculative decoding is not mesh-aware; "
                              "the fixed-slot pool serves this mesh "
                              "and the draft model is inert")
                if reason is None:
                    from kubeflow_tpu.models.paged import (
                        PagedDecodeScheduler,
                    )

                    self._scheduler = PagedDecodeScheduler(
                        self.model, self.params, mesh=self.mesh,
                        telemetry=lambda: self.telemetry,
                        draft_model=self.draft_model,
                        draft_params=self.draft_params,
                    )
                else:
                    from kubeflow_tpu.models.scheduler import (
                        DecodeScheduler,
                    )

                    self.scheduler_fallback = {
                        "reason": reason, "detail": detail}
                    if self.telemetry is not None and hasattr(
                            self.telemetry, "paged_fallback"):
                        self.telemetry.paged_fallback.labels(
                            reason=reason).inc()
                    self._scheduler = DecodeScheduler(
                        self.model, self.params, mesh=self.mesh,
                        telemetry=lambda: self.telemetry,
                    )
            sched = self._scheduler
        return sched if sched.alive else None

    def _generate_scheduled(self, sched, rows, validate, *, temperature,
                            top_k, eos_token, seed, priority, deadline):
        """Continuous-batched request lifecycle: submit to the scheduler
        and wait, mapping the scheduler's admission/first-token/finish
        events onto the SAME span sequence the lock path traces
        (admit → queue → prefill → decode), so /debug/traces and the
        TTFT/per-token series read identically under either engine."""
        tel = self.telemetry
        t_arrival = time.perf_counter()
        tel.begin_request()
        try:
            with tel.span("admit"):
                prompt, mask, n = validate()
                tel.batch_rows.observe(len(rows))
                tel.input_tokens.inc(sum(len(r) for r in rows))
            tel.slots_total.set(sched.slots)
            # The validated padded arrays ride along so the scheduler's
            # admission prefill doesn't re-pad the rows (same arrays,
            # half the host-side prep per request).
            pending = sched.submit(
                rows, max_new_tokens=n, temperature=temperature,
                top_k=top_k, eos_token=eos_token, seed=seed,
                tokens=prompt, prompt_mask=mask,
                priority=priority, deadline=deadline)
            with tel.span("queue"):
                pending.wait_admitted()
            with tel.span("prefill", rows=len(rows)):
                pending.wait_first_token()
            tel.ttft.observe(pending.t_first - t_arrival)
            with tel.span("decode", tokens=n):
                result = pending.result()
            if n > 1:
                tel.per_token.observe(
                    (pending.t_done - pending.t_first) / (n - 1))
            tel.output_tokens.inc(_generated_token_count(result, eos_token))
            tel.finish_request("ok")
            return result
        except BaseException:
            tel.finish_request("error")
            raise

    def generate(self, rows, *, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 eos_token=_UNSET, seed: int = 0,
                 priority: Optional[int] = None,
                 deadline: Optional[float] = None):
        """``priority`` is a PRIORITY_CLASSES value (admission order
        under the scheduler; the lock path serializes regardless);
        ``deadline`` is an absolute ``time.monotonic()`` cutoff — a
        request still queued past it raises DeadlineExceeded instead of
        generating for a client that stopped waiting."""
        from kubeflow_tpu.models.generate import (
            generate,
            generate_decode,
            generate_prefill,
        )
        from kubeflow_tpu.models.scheduler import DEFAULT_PRIORITY

        if priority is None:
            priority = DEFAULT_PRIORITY

        if eos_token is _UNSET:
            eos_token = self.default_eos_token

        def validate():
            # prompt+new > max_seq_len additionally 400s via the generate
            # jits' own cache_len check (caught upstream as ValueError).
            return _validate_and_pad(
                rows, self.model.cfg.vocab_size,
                max_new_tokens=max_new_tokens,
                default_max=self.default_max_new_tokens,
                limit_new=self.model.cfg.max_seq_len,
                limit_source=self.model.cfg.max_seq_len,
                top_k=top_k, eos_token=eos_token,
                limit_rows=self.max_batch_rows,
            )

        sched = self._scheduler_or_none()
        if sched is not None:
            return self._generate_scheduled(
                sched, rows, validate, temperature=temperature,
                top_k=top_k, eos_token=eos_token, seed=seed,
                priority=priority, deadline=deadline)

        def run(tel, t_arrival, prompt, mask, n):
            _check_deadline(deadline)
            kw = dict(max_new_tokens=n, temperature=temperature,
                      top_k=top_k, eos_token=eos_token)
            if tel is None:
                # Un-instrumented library use: the one-shot jit — no
                # phase-boundary host sync, no cache materialized as a
                # jit output.  The split below buys telemetry only.
                out = generate(self.model, self.params, prompt,
                               prompt_mask=mask, rng=jax.random.key(seed),
                               **kw)
                return jax.device_get(out).tolist()
            # Two-phase generation: the prefill/decode jits run EXACTLY
            # the one-shot generate()'s ops (shared implementation,
            # pinned token-equal by tests/test_serve.py), split at the
            # phase boundary so the request trace gets real
            # prefill/decode spans and TTFT is the first token's actual
            # host arrival.
            with tel.span("prefill", rows=prompt.shape[0]):
                first, decode_state = generate_prefill(
                    self.model, self.params, prompt, prompt_mask=mask,
                    rng=jax.random.key(seed), **kw)
                # Device→host fetch of the first sampled token: the
                # completion barrier TTFT is defined against.
                jax.device_get(first)
            tel.ttft.observe(time.perf_counter() - t_arrival)
            t_decode = time.perf_counter()
            with tel.span("decode", tokens=n):
                out = generate_decode(
                    self.model, self.params, decode_state, **kw)
                result = jax.device_get(out).tolist()
            if n > 1:
                # Decode seconds per post-first token; the scan runs its
                # full fixed length regardless of early EOS, so this is
                # the honest per-token decode cost.
                tel.per_token.observe(
                    (time.perf_counter() - t_decode) / (n - 1))
            return result

        return _telemetry_request(self, rows, eos_token, validate, run)


class Seq2SeqGenerationService:
    """Same request contract as GenerationService, encoder-decoder models:
    ``tokens`` rows are SOURCE sequences; the response is the generated
    target continuation (T5 convention: BOS = pad id 0, EOS = 1).

    Deliberately EXEMPT from the continuous-batching scheduler: the
    encoder pass is not a prompt-cache prefill — decoder slots would
    each need their own cross-attention K/V against a different source
    length, which the fixed slot pool cannot express.  This class has no
    scheduler branch at all, so KFT_SERVE_SCHEDULER cannot mis-route it;
    requests always take the lock-serialized path (pinned by
    tests/test_scheduler.py)."""

    default_eos_token: Optional[int] = 1
    telemetry: Optional[ServeTelemetry] = None

    def __init__(self, model, params, *, default_max_new_tokens: int = 32,
                 max_target_len: int = 512, max_source_len: int = 4096,
                 max_batch_rows: int = 64):
        self.model = model
        self.params = params
        self.default_max_new_tokens = default_max_new_tokens
        # T5 configs carry no max_seq_len, so the request bounds live on
        # the service — without them one request can size the per-layer KV
        # caches (and the O(S^2) encoder) arbitrarily.
        self.max_target_len = max_target_len
        self.max_source_len = max_source_len
        self.max_batch_rows = max_batch_rows
        self._lock = threading.Lock()

    def generate(self, rows, *, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 eos_token=_UNSET, seed: int = 0,
                 priority: Optional[int] = None,
                 deadline: Optional[float] = None):
        # ``priority`` is accepted for wire uniformity but inert — the
        # lock path serializes in arrival order; ``deadline`` still
        # evicts a request that expired waiting on the lock.
        del priority
        from kubeflow_tpu.models.generate import generate_seq2seq

        if eos_token is _UNSET:
            eos_token = self.default_eos_token

        def validate():
            return _validate_and_pad(
                rows, self.model.cfg.vocab_size,
                max_new_tokens=max_new_tokens,
                default_max=self.default_max_new_tokens,
                limit_new=self.max_target_len,
                limit_source=self.max_source_len,
                top_k=top_k, eos_token=eos_token,
                limit_rows=self.max_batch_rows,
            )

        def run(tel, t_arrival, source, mask, n):
            _check_deadline(deadline)
            # Encoder-decoder generation stays one jit (the encoder pass
            # is not a prompt-cache prefill); the TTFT/per-token split
            # applies to the decoder-only service.
            with span_or_null(tel, "generate", tokens=n):
                out = generate_seq2seq(
                    self.model, self.params, source, source_mask=mask,
                    max_new_tokens=n, temperature=temperature,
                    top_k=top_k, eos_token=eos_token,
                    rng=jax.random.key(seed),
                )
                return jax.device_get(out).tolist()

        return _telemetry_request(self, rows, eos_token, validate, run)


def create_app(service: GenerationService, *, model_name: str = "model",
               revision: Optional[int] = None):
    """``revision``: the serving revision this replica runs (the
    InferenceService controller injects KFT_SERVE_REVISION; standalone
    servers default to 0) — exported as ``serve_replica_revision`` so
    rollout tests and dashboards can see which weights a replica
    actually serves."""
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    from kubeflow_tpu.models.scheduler import (
        DeadlineExceeded,
        PRIORITY_CLASSES,
    )
    from kubeflow_tpu.platform.web.framework import (
        App,
        HttpError,
        failure,
        json_response,
        success,
    )

    app = App("model-serve")
    # Per-app registry: one process can serve several models/tests without
    # duplicate-timeseries collisions.
    registry = CollectorRegistry()
    requests_total = Counter(
        "generate_requests_total", "Generation requests by outcome",
        ["outcome"], registry=registry,
    )
    request_seconds = Histogram(
        "generate_request_seconds",
        "Wall time of /v1/generate requests (includes any compile)",
        buckets=(0.05, 0.2, 1, 5, 20, 60, 180),
        registry=registry,
    )
    tokens_total = Counter(
        "generate_tokens_total", "Tokens generated", registry=registry,
    )
    # Requests refused without generating, by reason: "warming" (the
    # /readyz warm generate is still in flight — structured 503 +
    # Retry-After instead of queueing behind the compile), "deadline"
    # (X-KFT-Deadline-Seconds expired before or while queued — 504).
    rejected_total = Counter(
        "generate_rejected_total",
        "Generation requests refused without running, by reason",
        ["reason"], registry=registry,
    )
    if revision is None:
        from kubeflow_tpu.platform import config as _cfg

        revision = _cfg.env_int("KFT_SERVE_REVISION", 0)
    replica_revision = Gauge(
        "serve_replica_revision",
        "InferenceService revision this replica serves "
        "(KFT_SERVE_REVISION; 0 for standalone servers)",
        registry=registry,
    )
    replica_revision.set(revision)
    # Serve-path telemetry (telemetry/serve.py): queue/batch/TTFT/
    # per-token series in the same per-app registry, plus the per-request
    # tracer /debug/traces serves.  Attached to the service because the
    # service owns the lock and the prefill/decode phase boundary.
    tel = ServeTelemetry(registry, component=model_name)
    service.telemetry = tel

    @app.route("/healthz")
    def healthz(request):
        return success({"healthy": True})

    # One-token warm generate, run once and cached: Ready means "this
    # process has actually produced a token" — weights restored, the
    # decode path compiled for a minimal shape.  The InferenceService
    # rolling update gates its traffic flip on this (readinessProbe +
    # the controller's own pre-flip probe), so a replica that would
    # crash or compile-stall on its first request never takes traffic.
    warm = {"done": False, "seconds": None, "error": None,
            "inflight": False}
    warm_lock = threading.Lock()

    @app.route("/readyz")
    def readyz(request):
        with warm_lock:
            if not warm["done"]:
                warm["inflight"] = True
                t0 = time.perf_counter()
                try:
                    service.generate([[1]], max_new_tokens=1)
                except Exception as e:  # noqa: BLE001 — readiness must
                    # report the failure, not 500 with a stack dump
                    warm["error"] = f"{type(e).__name__}: {e}"
                else:
                    # Success is cached; a failure is retried on the next
                    # probe (a transient fault must not wedge readiness).
                    warm["error"] = None
                    warm["done"] = True
                finally:
                    warm["inflight"] = False
                warm["seconds"] = round(time.perf_counter() - t0, 3)
        if warm["error"] is not None:
            raise HttpError(503, f"warm generate failed: {warm['error']}")
        return success({"ready": True, "revision": revision,
                        "warm_generate_seconds": warm["seconds"]})

    # Same contract as the controllers' /debug/traces (platform/main.py),
    # including the DEBUG_TRACES=false opt-out: this port is as
    # unauthenticated as the health port, and per-request traces reveal
    # more than /metrics already does.
    from kubeflow_tpu.platform import config as _config

    debug_traces_enabled = _config.env_bool("DEBUG_TRACES", True)

    @app.route("/debug/traces")
    def debug_traces(request):
        if not debug_traces_enabled:
            raise HttpError(404, "debug traces disabled")
        try:
            n = int(request.args.get("n", ""))
        except ValueError:
            n = None
        # ONE implementation of the query contract, shared with the
        # controllers' endpoint (telemetry.trace.filter_traces;
        # docs/observability.md "The /debug/traces contract").
        from kubeflow_tpu.telemetry.trace import filter_traces

        return json_response({"traces": filter_traces(
            tel.tracer.recent(None), n=n,
            trace_id=request.args.get("trace_id"))})

    @app.route("/debug/profile")
    def debug_profile(request):
        # The serve half of /debug/profile (platform/main.py documents
        # the full query surface): folded stacks from the process-wide
        # registered profiler — request threads attribute to the model
        # component through the same Tracer seam as reconciles.  Same
        # DEBUG_TRACES gate as traces; 404 while no profiler runs.
        if not debug_traces_enabled:
            raise HttpError(404, "debug traces disabled")
        from werkzeug.wrappers import Response

        from kubeflow_tpu.telemetry import profiler as _profiler

        prof = _profiler.debug_profiler()
        if prof is None:
            raise HttpError(404, "no profiler registered")
        body = None
        if request.args.get("seconds"):
            try:
                body = prof.capture(float(request.args["seconds"]))
            except ValueError:
                body = None
        elif request.args.get("window"):
            try:
                body = prof.folded(int(request.args["window"]))
            except ValueError:
                body = None
        else:
            body = prof.folded()
        if body is None:
            raise HttpError(404, "no such profile window")
        return Response(body, mimetype="text/plain")

    @app.route("/debug/serve")
    def debug_serve(request):
        # The serving-engine debug surface (/debug/knobs sibling): which
        # scheduler actually serves, the STRUCTURED paged-fallback
        # reason when the fixed pool took over (counted by
        # serve_paged_fallback_total), live scheduler stats (pool
        # shards, dispatch-overlap ratio, page states), and the knob
        # registry snapshot.  Same gate as the other debug routes.
        if not debug_traces_enabled:
            raise HttpError(404, "debug traces disabled")
        sched = getattr(service, "_scheduler", None)
        engine = None
        if sched is not None:
            engine = type(sched).__name__
        return success({
            "engine": engine,
            "mesh": (dict(service.mesh.shape)
                     if getattr(service, "mesh", None) is not None
                     else None),
            "paged_fallback": getattr(service, "scheduler_fallback",
                                      None),
            "scheduler": sched.stats() if sched is not None else None,
            "knobs": _config.effective(),
        })

    @app.route("/metrics")
    def metrics(request):
        from werkzeug.wrappers import Response

        return Response(generate_latest(registry), mimetype="text/plain")

    @app.route("/v1/model")
    def model_info(request):
        cfg = service.model.cfg
        return success({
            "model": model_name,
            "config": {
                k: v for k, v in dataclasses.asdict(cfg).items()
                if isinstance(v, (int, float, str, bool))
            },
        })

    @app.route("/v1/generate", methods=["POST"])
    def generate(request):
        # Header passthrough (telemetry/causal.py): the shared web
        # framework already installed any caller-sent traceparent as the
        # request's current context (web/framework.App.__call__), so the
        # serve trace links into the caller's journey via
        # ServeTelemetry.begin_request reading causal.current() —
        # nothing to re-parse here.  The deadline and priority ride the
        # same passthrough as headers the activator forwards verbatim.
        body = request.get_json(force=True, silent=True) or {}
        t0 = time.perf_counter()
        try:  # noqa: SIM105 — latency must cover every outcome
            if warm["inflight"] and not warm["done"]:
                # Not yet warm: the /readyz warm generate is compiling
                # the decode path right now.  A structured 503 with a
                # Retry-After beats queueing this request behind a
                # multi-second compile — the activator (or any client)
                # replays it once readiness flips.
                rejected_total.labels(reason="warming").inc()
                return failure(
                    "replica not warm: /readyz warm generate in flight",
                    503, headers={"Retry-After": "2"})
            try:
                priority, deadline = _qos_headers(request)
            except ValueError as e:
                requests_total.labels(outcome="invalid").inc()
                raise HttpError(400, str(e)) from None
            if deadline is not None and time.monotonic() >= deadline:
                rejected_total.labels(reason="deadline").inc()
                requests_total.labels(outcome="deadline").inc()
                return failure("request deadline already expired", 504)
            return _generate(body, priority, deadline)
        finally:
            request_seconds.observe(time.perf_counter() - t0)

    def _qos_headers(request):
        """(priority, absolute-monotonic deadline) from the QoS headers;
        raises ValueError (→400) on a malformed value."""
        priority = None
        name = request.headers.get("X-KFT-Priority")
        if name:
            if name not in PRIORITY_CLASSES:
                raise ValueError(
                    f"unknown priority class {name!r}; expected one of "
                    f"{sorted(PRIORITY_CLASSES)}")
            priority = PRIORITY_CLASSES[name]
        deadline = None
        raw = request.headers.get("X-KFT-Deadline-Seconds")
        if raw:
            try:
                secs = float(raw)
            except ValueError:
                raise ValueError(
                    f"malformed X-KFT-Deadline-Seconds {raw!r}") from None
            deadline = time.monotonic() + secs
        return priority, deadline

    def _generate(body, priority, deadline):
        try:
            # int()/float() coercions raise TypeError on null/list inputs —
            # every malformed field must land as a 400, not a 500.
            kwargs = {}
            if "eos_token" in body:
                # Only forward when the client set it, so each service's
                # own default applies (seq2seq defaults to EOS=1).
                kwargs["eos_token"] = body["eos_token"]
            tokens = service.generate(
                body.get("tokens"),
                max_new_tokens=body.get("max_new_tokens"),
                temperature=float(body.get("temperature", 0.0)),
                top_k=body.get("top_k"),
                seed=int(body.get("seed", 0)),
                priority=priority, deadline=deadline,
                **kwargs,
            )
        except DeadlineExceeded as e:
            # The budget expired while queued (scheduler or lock): a
            # structured 504 — the caller must NOT replay a dead request.
            rejected_total.labels(reason="deadline").inc()
            requests_total.labels(outcome="deadline").inc()
            return failure(str(e), 504)
        except (ValueError, TypeError) as e:
            requests_total.labels(outcome="invalid").inc()
            raise HttpError(400, str(e)) from None
        except Exception:
            requests_total.labels(outcome="error").inc()
            raise
        requests_total.labels(outcome="ok").inc()
        eos = body.get("eos_token", service.default_eos_token)
        tokens_total.inc(_generated_token_count(tokens, eos))
        return success({"tokens": tokens})

    return app


def load_service(
    model_name: str, *, checkpoint_dir: Optional[str] = None,
    max_seq_len: Optional[int] = None,
    seed: int = 0, quantize: Optional[str] = None,
    mesh_spec: Optional[str] = None,
    draft_model_name: Optional[str] = None,
    draft_checkpoint_dir: Optional[str] = None,
) -> "GenerationService | Seq2SeqGenerationService":
    """Build the model; restore params from a train-loop checkpoint when
    given, else random-init (useful for smoke/serving-path tests).

    ``draft_model_name`` builds a second, smaller decoder for
    speculative decoding under the paged scheduler — the registry
    already carries small llamas to draft for big ones.  The draft must
    share the target's vocab (its proposals index the target's token
    space) and is validated here so a mismatch fails at startup, not on
    the first speculative step."""
    from kubeflow_tpu.models import create_model

    model = create_model(model_name)
    if max_seq_len:
        if hasattr(model.cfg, "max_seq_len"):
            model = create_model(model_name, max_seq_len=max_seq_len)
        else:
            # Don't silently drop an explicit operator request.
            raise ValueError(
                f"{model_name} has no max_seq_len config; drop --max-seq-len"
            )
    # Encoder-decoder models expose encode/decode apply methods and init
    # with a (source, target) pair; decoder-only models init with tokens.
    seq2seq = hasattr(model, "encode")
    mesh = None
    if mesh_spec:
        # Validate the SPMD flags BEFORE the (potentially multi-GB)
        # checkpoint restore — a typo'd spec must fail in milliseconds.
        if quantize:
            raise ValueError("--mesh with --quantize is not supported yet "
                             "(QTensor leaves carry their own layouts)")
        from kubeflow_tpu.parallel.sharding import rules_for_model
        from kubeflow_tpu.train.run import parse_mesh

        rules = rules_for_model(model)
        mesh = parse_mesh(mesh_spec, len(jax.devices()))
    tokens = jnp.ones((1, 8), jnp.int32)
    init_args = (tokens, jnp.ones((1, 4), jnp.int32)) if seq2seq else (tokens,)
    if checkpoint_dir:
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        # Shape-only init: the dtype/structure template costs nothing when
        # the checkpoint supplies every value.
        template = jax.eval_shape(
            lambda: model.init(jax.random.key(seed), *init_args)
        )["params"]
        if mesh is not None:
            # Restore DIRECTLY into the mesh-sharded layout: a model
            # larger than one chip's HBM must never materialize
            # replicated on device 0 first.
            from jax.sharding import NamedSharding

            from kubeflow_tpu.parallel.sharding import tree_specs

            specs = tree_specs(template, rules)
            template = jax.tree.map(
                lambda t, s: jax.ShapeDtypeStruct(
                    t.shape, t.dtype, sharding=NamedSharding(mesh, s)
                ),
                template, specs,
            )
        with CheckpointManager(checkpoint_dir) as mgr:
            # Params-only restore: serving doesn't know (or need) the
            # optimizer the checkpoint was trained with.
            params = mgr.restore_params(template=template)
        if params is None:
            raise FileNotFoundError(
                f"no checkpoint found under {checkpoint_dir}"
            )
    else:
        params = model.init(jax.random.key(seed), *init_args)["params"]
    if quantize:
        if quantize != "int8":
            raise ValueError(f"unsupported quantization {quantize!r} (int8)")
        from kubeflow_tpu.models.quantize import quantize_params

        # Weight-only int8: halves HBM bytes per decoded token; generate()
        # dequantizes inside the jit so the widening fuses into matmuls.
        params = quantize_params(params)
    if mesh is not None and not checkpoint_dir:
        # SPMD serving, random-init path: place params sharded over the
        # mesh by the family rules (the checkpoint path above already
        # restored directly into the sharded layout); the jitted generate
        # path then runs tensor-parallel, XLA inserting the collectives.
        from kubeflow_tpu.parallel.sharding import shard_params

        params = shard_params(params, mesh, rules)
    if seq2seq:
        if draft_model_name:
            raise ValueError(
                "--draft-model applies to decoder-only serving; seq2seq "
                "models have no speculative-decoding path")
        return Seq2SeqGenerationService(model, params)
    draft_model = draft_params = None
    if draft_model_name:
        draft_model = create_model(draft_model_name)
        if hasattr(draft_model, "encode"):
            raise ValueError(
                f"draft model {draft_model_name} is seq2seq; speculative "
                f"decoding needs a decoder-only draft")
        if draft_model.cfg.vocab_size != model.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_model.cfg.vocab_size} != target "
                f"vocab {model.cfg.vocab_size}: the draft's proposals "
                f"must index the target's token space")
        if draft_checkpoint_dir:
            from kubeflow_tpu.train.checkpoint import CheckpointManager

            template = jax.eval_shape(
                lambda: draft_model.init(
                    jax.random.key(seed), jnp.ones((1, 8), jnp.int32))
            )["params"]
            with CheckpointManager(draft_checkpoint_dir) as mgr:
                draft_params = mgr.restore_params(template=template)
            if draft_params is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {draft_checkpoint_dir}")
        else:
            draft_params = draft_model.init(
                jax.random.key(seed), jnp.ones((1, 8), jnp.int32)
            )["params"]
    # The mesh rides on the service so the continuous-batching scheduler
    # can batch-shard its slot pool over the same device mesh the params
    # are sharded across.
    return GenerationService(model, params, mesh=mesh,
                             draft_model=draft_model,
                             draft_params=draft_params)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="llama_125m")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--quantize", choices=["int8"], default=None,
                    help="weight-only int8 serving (halved HBM per token)")
    ap.add_argument("--mesh", default=None,
                    help="SPMD serving: shard params over a mesh, e.g. "
                         "'tp=4' (tensor parallel across 4 chips)")
    ap.add_argument("--draft-model", default=None,
                    help="small same-vocab decoder for speculative "
                         "decoding under the paged scheduler "
                         "(KFT_SERVE_SPEC_TOKENS proposals per step)")
    ap.add_argument("--draft-checkpoint-dir", default=None,
                    help="checkpoint for --draft-model (random-init "
                         "when omitted — smoke/test use only)")
    args = ap.parse_args(argv)

    try:
        service = load_service(
            args.model, checkpoint_dir=args.checkpoint_dir,
            max_seq_len=args.max_seq_len, quantize=args.quantize,
            mesh_spec=args.mesh,
            draft_model_name=args.draft_model,
            draft_checkpoint_dir=args.draft_checkpoint_dir,
        )
    except (ValueError, FileNotFoundError) as e:
        ap.error(str(e))  # clean CLI exit, not a traceback
    app = create_app(service, model_name=args.model)
    from werkzeug.serving import make_server

    server = make_server("0.0.0.0", args.port, app, threaded=True)
    print(json.dumps({"serving": args.model, "port": args.port}), flush=True)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
