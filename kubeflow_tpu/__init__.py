"""kubeflow_tpu — a TPU-native notebook platform and in-notebook compute stack.

This package is a ground-up, TPU-first rebuild of the capabilities of the
Kubeflow notebooks platform (reference: kubeflow/kubeflow). It has two halves:

* ``kubeflow_tpu.platform`` — the control plane: CRD types, reconcilers
  (Notebook/Profile/Tensorboard/culling), the PodDefault mutating admission
  webhook, access management (KFAM), CRUD web-app backends and the central
  dashboard, all speaking to the Kubernetes API through a small native REST
  client.  Where the reference platform schedules ``nvidia.com/gpu`` pods,
  this one schedules ``google.com/tpu`` slices (single- and multi-host) with
  topology-aware node selectors and TPU worker env injection.

* ``kubeflow_tpu.models`` / ``ops`` / ``parallel`` / ``train`` — the
  in-notebook compute stack shipped in the platform's notebook images:
  JAX/Flax model families (ResNet, ViT, BERT, Llama), Pallas TPU kernels
  (flash attention, fused norms), and SPMD parallelism utilities
  (mesh construction, dp/fsdp/tp/sp sharding rules, ring attention) that the
  reference platform left entirely to user code inside CUDA images.
"""

__version__ = "0.1.0"
