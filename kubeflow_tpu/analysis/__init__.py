"""kftlint — repo-native invariant linting for the control plane.

The platform's hardest bugs (fence-overlap, stale-generation pod kill,
status-merge wipe) were violations of *repo-specific* contracts — fenced
writes, frozen-view reads, status-via-patch, jax-free controllers — that
generic linters cannot know about.  This package checks them statically,
the way the reference Kubeflow repo leans on golangci-lint for its
controller tree:

* ``engine``  — AST lint driver: rule registry, per-line / per-file
  ``# kft: disable=RULE`` suppressions, a checked-in baseline so a new
  rule can land green and ratchet down.
* ``rules``   — the repo-native rule set (R001..R009); see
  docs/analysis.md for the rule reference.

Run it over the tree (repo root cwd)::

    python -m kubeflow_tpu.analysis --baseline ci/kftlint_baseline.json

Exit is nonzero on any unsuppressed, un-baselined finding — the ``lint``
presubmit lane in ci/workflows.py gates on it.
"""
from kubeflow_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Rule,
    all_rules,
    lint_paths,
    lint_source,
    load_baseline,
    write_baseline,
)
from kubeflow_tpu.analysis import rules as _rules  # noqa: F401  (registers)
