"""kftlint engine: rule registry, suppressions, baseline, file driver.

Design (mirrors how golangci-lint serves the reference repo, shrunk to
this repo's needs):

* A **Rule** owns an id (``R00x``), a one-line summary, and scope globs —
  the repo subtrees where its invariant holds.  ``check(tree, text,
  path)`` yields ``(lineno, message)`` findings for one file; rules that
  need cross-file state (duplicate metric names) override ``finalize()``.
  Rules are registered by factory so every run gets fresh instances.

* **Suppressions** are source comments, closest-wins:
  ``# kft: disable=R005 reason`` on the finding line (or on a standalone
  comment line directly above it) silences those rules for that line;
  ``# kft: disable-file=R003 reason`` anywhere in the file silences the
  whole file.  A reason is not parsed but reviewers expect one.

* The **baseline** is a checked-in JSON set of finding fingerprints —
  rule id + path + the *normalized source line* (plus a duplicate index),
  so unrelated edits above a baselined finding do not resurface it, while
  touching the offending line itself does.  A new rule lands green by
  baselining its existing findings and ratcheting to zero; the shipped
  baseline is empty because every current finding is fixed or carries an
  inline suppression with a reason (docs/analysis.md "Baseline
  workflow").
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_LINE_RE = re.compile(r"#\s*kft:\s*disable=([A-Za-z0-9_,]+)")
SUPPRESS_FILE_RE = re.compile(r"#\s*kft:\s*disable-file=([A-Za-z0-9_,]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int          # 1-based
    message: str
    fingerprint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class Rule:
    """One invariant.  Subclasses implement ``check``; ``scope``/``exclude``
    are fnmatch globs over repo-relative paths (fnmatch ``*`` crosses
    ``/``, so ``kubeflow_tpu/platform/controllers/*.py`` covers the whole
    subtree)."""

    id: str = ""
    summary: str = ""
    scope: Sequence[str] = ()
    exclude: Sequence[str] = ()

    def applies(self, path: str) -> bool:
        if any(fnmatch.fnmatch(path, g) for g in self.exclude):
            return False
        return any(fnmatch.fnmatch(path, g) for g in self.scope)

    def check(self, tree: ast.AST, text: str, path: str) -> Iterable[Tuple[int, str]]:
        raise NotImplementedError

    def finalize(self) -> List[Finding]:
        return []


_REGISTRY: Dict[str, type] = {}


def register(rule_cls: type) -> type:
    """Class decorator adding a Rule subclass to the registry (keyed by id;
    a duplicate id is a programming error, not a merge surprise)."""
    rid = rule_cls.id
    if rid in _REGISTRY:
        raise ValueError(f"duplicate rule id {rid}")
    _REGISTRY[rid] = rule_cls
    return rule_cls


def all_rules() -> List[Rule]:
    """Fresh rule instances for one run (cross-file rules carry state)."""
    return [cls() for cls in _REGISTRY.values()]


# -- suppressions -------------------------------------------------------------


def _suppressions(text: str) -> Tuple[Set[str], Dict[int, Set[str]]]:
    """(file-wide suppressed rule ids, per-line suppressed rule ids).

    A standalone ``# kft: disable=...`` comment line suppresses the next
    line too, so long findings can carry the reason above them."""
    file_wide: Set[str] = set()
    by_line: Dict[int, Set[str]] = {}
    lines = text.splitlines()
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_FILE_RE.search(line)
        if m:
            file_wide |= set(m.group(1).split(","))
        m = SUPPRESS_LINE_RE.search(line)
        if m:
            rules = set(m.group(1).split(","))
            by_line.setdefault(i, set()).update(rules)
            if line.lstrip().startswith("#"):
                by_line.setdefault(i + 1, set()).update(rules)
    return file_wide, by_line


# -- fingerprints -------------------------------------------------------------


def _fingerprint(rule: str, path: str, norm_line: str, dup_index: int) -> str:
    h = hashlib.sha256(
        f"{rule}|{path}|{norm_line}|{dup_index}".encode()
    ).hexdigest()
    return h[:16]


def _attach_fingerprints(findings: List[Finding],
                         texts: Dict[str, str]) -> List[Finding]:
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        lines = texts.get(f.path, "").splitlines()
        norm = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        key = (f.rule, f.path, norm)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        out.append(dataclasses.replace(
            f, fingerprint=_fingerprint(f.rule, f.path, norm, idx)))
    return out


# -- baseline -----------------------------------------------------------------


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    if not os.path.exists(path):
        return set()
    with open(path) as fh:
        data = json.load(fh)
    return {(e["rule"], e["path"], e["fingerprint"])
            for e in data.get("findings", [])}


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    data = {
        "version": 1,
        "findings": [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint}
            for f in sorted(findings,
                            key=lambda f: (f.rule, f.path, f.fingerprint))
        ],
    }
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


# -- driver -------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", "node_modules"}


def _walk_default(root: str) -> List[str]:
    """Default lint set: every .py under kubeflow_tpu/ (rule scopes narrow
    further)."""
    out = []
    base = os.path.join(root, "kubeflow_tpu")
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fn in filenames:
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(p.replace(os.sep, "/") for p in out)


def lint_source(text: str, path: str,
                rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Lint one in-memory file as if it lived at ``path`` (the corpus
    tests route bad/good twins through rule scopes this way).  Applies
    suppressions but not baselines; fingerprints are attached."""
    rules = list(rules) if rules is not None else all_rules()
    findings = _lint_one(text, path, rules)
    for r in rules:
        findings.extend(r.finalize())
    return _filter_suppressed(_attach_fingerprints(findings, {path: text}),
                              {path: text})


def _lint_one(text: str, path: str, rules: Sequence[Rule]) -> List[Finding]:
    applicable = [r for r in rules if r.applies(path)]
    if not applicable:
        return []
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding("E000", path, e.lineno or 1,
                        f"syntax error: {e.msg}")]
    found = []
    for r in applicable:
        for line, msg in r.check(tree, text, path):
            found.append(Finding(r.id, path, line, msg))
    return found


def _filter_suppressed(findings: List[Finding],
                       texts: Dict[str, str]) -> List[Finding]:
    sup_cache: Dict[str, Tuple[Set[str], Dict[int, Set[str]]]] = {}
    out = []
    for f in findings:
        if f.path not in sup_cache:
            sup_cache[f.path] = _suppressions(texts.get(f.path, ""))
        file_wide, by_line = sup_cache[f.path]
        if f.rule in file_wide or f.rule in by_line.get(f.line, ()):
            continue
        out.append(f)
    return out


def lint_paths(paths: Optional[Sequence[str]] = None, *,
               root: str = ".") -> List[Finding]:
    """Lint ``paths`` (repo-relative; default: the kubeflow_tpu tree under
    ``root``).  Returns unsuppressed findings with fingerprints attached;
    baseline subtraction is the caller's move (``load_baseline``)."""
    rels = list(paths) if paths else _walk_default(root)
    rules = all_rules()
    findings: List[Finding] = []
    texts: Dict[str, str] = {}
    for rel in rels:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        texts[rel] = text
        findings.extend(_lint_one(text, rel, rules))
    for r in rules:
        findings.extend(r.finalize())
    return _filter_suppressed(_attach_fingerprints(findings, texts), texts)
