"""CLI: ``python -m kubeflow_tpu.analysis [paths...]``.

Exit codes: 0 clean (after suppressions + baseline), 1 findings, 2 usage.
The ``lint`` presubmit lane (ci/workflows.py) runs::

    python -m kubeflow_tpu.analysis --baseline ci/kftlint_baseline.json

``--write-baseline`` rewrites the baseline from the current findings —
the ratchet move when landing a new rule over existing debt.
"""
from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.analysis import engine
from kubeflow_tpu.analysis import rules as _rules  # noqa: F401  (registers)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="kftlint: repo-native invariant linting (docs/analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files to lint (default: kubeflow_tpu/)")
    ap.add_argument("--root", default=".",
                    help="repo root the paths/scopes resolve against")
    ap.add_argument("--baseline", default=None,
                    help="JSON baseline; matching findings don't fail the run")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline from the current findings")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in engine.all_rules():
            print(f"{rule.id}  {rule.summary}")
        return 0

    findings = engine.lint_paths(args.paths or None, root=args.root)

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline", file=sys.stderr)
            return 2
        engine.write_baseline(findings, args.baseline)
        print(f"baseline: wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = engine.load_baseline(args.baseline) if args.baseline else set()
    new = [f for f in findings
           if (f.rule, f.path, f.fingerprint) not in baseline]
    baselined = len(findings) - len(new)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.__dict__ for f in new],
            "baselined": baselined,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        print(f"kftlint: {len(new)} finding(s), {baselined} baselined")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
