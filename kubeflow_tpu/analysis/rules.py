"""The repo-native rule set (R001..R010).

Each rule encodes a contract a past PR bled for — the rationale, an
example finding, and the sanctioned fix live in docs/analysis.md.  Rules
are deliberately *precise over complete*: a rule that cries wolf on
``limits.update(...)`` (a dict, not a client) would be suppressed into
noise within two PRs, so receivers are matched structurally.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from kubeflow_tpu.analysis.engine import Finding, Rule, register

CONTROLLERS = "kubeflow_tpu/platform/controllers/*.py"
RUNTIME = "kubeflow_tpu/platform/runtime/*.py"


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``self.client.inner`` -> ["self", "client", "inner"]; None for
    receivers that are not plain Name/Attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


WRITE_VERBS = {
    "create", "update", "patch", "delete", "update_status",
    "patch_status", "replace", "delete_collection",
}
CLIENT_CLASSES = {"RestKubeClient", "HttpKube", "FakeKube", "ChaosKube"}


@register
class FencedWrites(Rule):
    """R001: reconcile-path writes go through the controller's injected
    client (``self.client`` — the FencedClient when sharding is on) or the
    ``runtime.apply`` helpers.  A write on any *other* client-shaped
    receiver — ``.inner`` (the fence bypass), a locally constructed
    transport client, a sibling informer's client — escapes the fence and
    re-opens the PR-8 split-brain double-write."""

    id = "R001"
    summary = ("reconcile-path writes must go through the injected "
               "self.client / apply.* helpers, never a raw client")
    scope = (CONTROLLERS,)

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in CLIENT_CLASSES:
                yield (node.lineno,
                       f"constructs {fn.id} inside a controller; clients "
                       "are wired in main.py and injected (fencing wraps "
                       "the injected one)")
                continue
            if not (isinstance(fn, ast.Attribute) and fn.attr in WRITE_VERBS):
                continue
            recv = fn.value
            if isinstance(recv, ast.Call):
                if _call_name(recv) in CLIENT_CLASSES:
                    yield (node.lineno,
                           f"write via inline {_call_name(recv)}() bypasses "
                           "the manager's FencedClient wiring")
                continue
            chain = _attr_chain(recv)
            if chain is None:
                continue
            if "inner" in chain:
                yield (node.lineno,
                       f"write via {'.'.join(chain)}.{fn.attr} bypasses the "
                       "write fence; use the fenced client itself")
                continue
            term = chain[-1].lower()
            if (("client" in term or "kube" in term)
                    and chain not in (["self", "client"], ["client"])):
                yield (node.lineno,
                       f"raw client write {'.'.join(chain)}.{fn.attr}(); "
                       "route through the injected self.client "
                       "(FencedClient) or runtime.apply helpers")


_INFORMERISH = ("informer", "cache", "lister")
_GETTERS = {"get", "list", "index_list"}
_MUTATORS = {
    "setdefault", "update", "pop", "popitem", "clear", "append",
    "extend", "insert", "remove", "sort", "reverse",
}


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class FrozenViews(Rule):
    """R002: objects read from an informer cache are enforced-read-only
    frozen views (docs/performance.md "read-ownership contract"); writing
    into one without ``thaw()`` either raises at runtime or — worse, on a
    plain-dict test double — silently mutates the shared cache every other
    reader trusts.  Tracks names bound from ``*informer*/*cache*``
    ``get/list/index_list`` within a function and flags subscript/attribute
    stores and mutating method calls on them until they are re-bound
    (``thaw(obj)``, ``dict(obj)``, ``copy.deepcopy(obj)``...)."""

    id = "R002"
    summary = "informer-cached objects must be thaw()ed before mutation"
    scope = (CONTROLLERS, RUNTIME)

    def check(self, tree, text, path):
        out: List[Tuple[int, str]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, out)
        return out

    def _informerish(self, call: ast.Call) -> bool:
        fn = call.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _GETTERS):
            return False
        chain = _attr_chain(fn.value)
        if chain is None:
            # informers[GVK].get(...) — subscripted receiver IS a cache
            base = _root_name(fn.value)
            return any(m in (base or "").lower() for m in _INFORMERISH)
        # Plural terminals (self.informers.get(gvk), caches.get(...)) are
        # containers OF informers — their .get returns an Informer object,
        # not a frozen view.
        term = chain[-1].lower()
        if term.endswith("s"):
            return False
        return any(m in part.lower() for part in chain for m in _INFORMERISH)

    def _scan_function(self, func, out: List[Tuple[int, str]]) -> None:
        tracked: set = set()

        def visit(stmts) -> None:
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs scanned by the outer walk
                if isinstance(st, ast.Assign):
                    self._flag_stores(st.targets, tracked, st.lineno, out)
                    self._rebind(st.targets, st.value, tracked)
                    self._flag_calls(st.value, tracked, out)
                elif isinstance(st, ast.AugAssign):
                    self._flag_stores([st.target], tracked, st.lineno, out)
                elif isinstance(st, ast.For):
                    if (isinstance(st.target, ast.Name)
                            and self._iter_tracked(st.iter, tracked)):
                        tracked.add(st.target.id)
                    self._flag_calls(st.iter, tracked, out)
                    visit(st.body)
                    visit(st.orelse)
                elif isinstance(st, (ast.If, ast.While)):
                    self._flag_calls(st.test, tracked, out)
                    visit(st.body)
                    visit(st.orelse)
                elif isinstance(st, ast.With):
                    visit(st.body)
                elif isinstance(st, ast.Try):
                    visit(st.body)
                    for h in st.handlers:
                        visit(h.body)
                    visit(st.orelse)
                    visit(st.finalbody)
                elif isinstance(st, ast.Expr):
                    self._flag_calls(st.value, tracked, out)
                elif isinstance(st, ast.Return) and st.value is not None:
                    self._flag_calls(st.value, tracked, out)
        visit(func.body)

    def _iter_tracked(self, it: ast.AST, tracked) -> bool:
        if isinstance(it, ast.Name) and it.id in tracked:
            return True
        return isinstance(it, ast.Call) and self._informerish(it)

    def _rebind(self, targets, value, tracked) -> None:
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if isinstance(value, ast.Call) and self._informerish(value):
                tracked.add(t.id)
            elif isinstance(value, ast.Name) and value.id in tracked:
                tracked.add(t.id)
            else:
                tracked.discard(t.id)

    def _flag_stores(self, targets, tracked, lineno, out) -> None:
        # Subscript stores only: item assignment is what FrozenResource
        # forbids; attribute stores on tracked names are overwhelmingly
        # Informer-object configuration, not cache mutation.
        for t in targets:
            if isinstance(t, ast.Subscript):
                base = _root_name(t)
                if base in tracked:
                    out.append((
                        lineno,
                        f"assigns into '{base}', a frozen informer view; "
                        "thaw() it first (intent-to-write deep copy)"))

    def _flag_calls(self, expr: ast.AST, tracked, out) -> None:
        for node in ast.walk(expr):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                base = _root_name(node.func.value)
                if base in tracked:
                    out.append((
                        node.lineno,
                        f"calls .{node.func.attr}() on '{base}', a frozen "
                        "informer view; thaw() it first"))


_JAX_ROOTS = {"jax", "jaxlib", "flax", "optax"}
_HEAVY_PREFIXES = (
    "kubeflow_tpu.models", "kubeflow_tpu.ops", "kubeflow_tpu.train",
)


@register
class JaxFreeControlPlane(Rule):
    """R003: the control plane imports no jax at module import time — a
    controller pod must start (and restart fast during chaos) without
    paying XLA init, and the PR-9 weld keeps the accelerator stack on the
    workload side of the CRD boundary.  Function-local imports are the
    sanctioned escape for test-only or lazily-used paths."""

    id = "R003"
    summary = ("platform/controllers and platform/runtime must be "
               "import-time jax-free")
    scope = (CONTROLLERS, RUNTIME)

    def check(self, tree, text, path):
        for st in self._module_level(tree.body):
            mods: List[str] = []
            if isinstance(st, ast.Import):
                mods = [a.name for a in st.names]
            elif isinstance(st, ast.ImportFrom) and st.module:
                # `from kubeflow_tpu import models` imports the heavy
                # submodule just as surely as `import kubeflow_tpu.models`
                # — check module+name joins, not just the module.
                mods = [st.module] + [f"{st.module}.{a.name}"
                                      for a in st.names]
            for mod in mods:
                root = mod.split(".")[0]
                if root in _JAX_ROOTS or mod.startswith(_HEAVY_PREFIXES):
                    yield (st.lineno,
                           f"module-level import of '{mod}' drags the "
                           "accelerator stack into control-plane import "
                           "time; import inside the function that needs it")

    def _module_level(self, body) -> Iterable[ast.stmt]:
        for st in body:
            yield st
            if isinstance(st, ast.If):       # TYPE_CHECKING / version gates
                yield from self._module_level(st.body)
                yield from self._module_level(st.orelse)
            elif isinstance(st, ast.Try):    # optional-dep probing
                yield from self._module_level(st.body)
                for h in st.handlers:
                    yield from self._module_level(h.body)


@register
class StatusViaPatch(Rule):
    """R004: status writes go through ``apply.patch_status_diff`` — a
    diff'd merge patch on the status subresource — never ``update_status``
    (a full-object status PUT).  The PR-11 status-merge wipe was exactly a
    full status write racing a sibling field owner."""

    id = "R004"
    summary = "status writes use apply.patch_status_diff, never update_status"
    scope = (CONTROLLERS, RUNTIME)
    exclude = ("kubeflow_tpu/platform/runtime/apply.py",)

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update_status"):
                yield (node.lineno,
                       "full-object update_status() can wipe sibling status "
                       "owners; use apply.patch_status_diff (merge patch of "
                       "the changed subtree)")


@register
class KnobRegistry(Rule):
    """R005: every environment knob resolves through the single-source
    registry in ``platform/config.py`` (``config.knob`` / ``config.env*``)
    so /debug/knobs can enumerate the live surface and docs stay honest.
    A stray ``os.environ`` literal is an undocumented, undumpable knob."""

    id = "R005"
    summary = "env knobs resolve through config.knob, not raw os.environ"
    scope = ("kubeflow_tpu/*.py",)
    exclude = (
        "kubeflow_tpu/platform/config.py",   # the registry itself
        "kubeflow_tpu/analysis/*.py",
    )

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            # `from os import environ` aliases the mapping out from under
            # the receiver check — flag the import itself.
            if (isinstance(node, ast.ImportFrom) and node.module == "os"
                    and any(a.name in ("environ", "getenv")
                            for a in node.names)):
                yield (node.lineno,
                       "importing environ/getenv from os hides env reads "
                       "from the registry; import os and resolve through "
                       "config.knob")
            elif (isinstance(node, ast.Attribute) and node.attr == "environ"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "os"):
                yield (node.lineno,
                       "raw os.environ read; resolve through config.knob("
                       "name, default, parser) so /debug/knobs sees it")
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "getenv"
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "os"):
                yield (node.lineno,
                       "raw os.getenv; resolve through config.knob(name, "
                       "default, parser) so /debug/knobs sees it")


_BROAD = {"Exception", "BaseException"}


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts)
    return False


@register
class NoSilentExcept(Rule):
    """R006: a broad ``except Exception: pass`` in control-plane code
    swallows the first symptom of every future bug.  The handler must at
    least debug-log with ``exc_info`` or bump a counter; where swallowing
    IS the contract (interpreter-shutdown ``__del__``), say so with an
    inline ``# kft: disable=R006 <reason>``."""

    id = "R006"
    summary = "no bare `except Exception: pass` without a log or counter"
    scope = (
        CONTROLLERS, RUNTIME,
        "kubeflow_tpu/platform/webhook/*.py",
        "kubeflow_tpu/platform/k8s/*.py",
        "kubeflow_tpu/platform/native.py",
    )

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                yield (node.lineno,
                       "broad except swallows the error silently; "
                       "log.debug(..., exc_info=True), bump a counter, or "
                       "disable with a reason")


_METRIC_CTORS = {"Counter", "Gauge", "Histogram", "Summary"}
# Modules that own metric declarations: the two halves' metric surfaces
# plus the model server's own series.
_METRIC_MODULES = {
    "kubeflow_tpu/platform/runtime/metrics.py",
    "kubeflow_tpu/telemetry/metrics.py",
    "kubeflow_tpu/telemetry/compute.py",
    "kubeflow_tpu/telemetry/serve.py",
    "kubeflow_tpu/models/serve.py",
}
# Bounded label keys.  Label VALUES must be bounded too (that part is a
# review judgment), but a label key outside this list is either a typo or
# a new cardinality decision that belongs in docs/observability.md first.
_LABEL_ALLOWLIST = {
    "controller", "result", "verb", "kind", "reason", "direction",
    "profile", "shard", "component", "queue", "name", "engine", "code",
    "method", "phase", "model", "app", "severity", "device", "le",
    "outcome", "pool", "action", "impl",
    # ISSUE 15 (the fleet metrics pipeline; docs/observability.md "The
    # metrics pipeline"): "alert" is bounded by the declared SLO rule
    # set, "state" by the fixed alert/goodput state vocabularies.
    "alert", "state",
    # ISSUE 16 (continuous profiling; docs/observability.md "Profiling
    # and incidents"): "role" is bounded by the attribution seams —
    # controller/component names, registered pool names, and stripped
    # long-lived thread names; default Thread-N names all fold into the
    # single "unattributed" value.
    "role",
    # ISSUE 19 (the serving front door; docs/serving.md "The front
    # door"): "tenant" is bounded by the profile set — the activator's
    # X-KFT-Tenant values are profile namespaces (plus "default"), the
    # same bounded vocabulary the quota ledger keys on.
    "tenant",
}


@register
class MetricHygiene(Rule):
    """R007: metric names are declared once, in a metrics module, with
    label keys from the bounded allowlist.  Duplicate names stack
    collectors on re-import (the PR-1 registry-hygiene lesson); ad-hoc
    label keys are where cardinality explosions start."""

    id = "R007"
    summary = ("metrics declared once in a metrics module; label keys "
               "from the bounded set")
    scope = ("kubeflow_tpu/*.py",)

    def __init__(self):
        self._names: Dict[str, List[Tuple[str, int]]] = {}

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) in _METRIC_CTORS):
                continue
            # Prometheus ctors take (name, documentation, ...): two leading
            # string literals — collections.Counter never looks like this.
            if not (len(node.args) >= 2
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                continue
            mname = node.args[0].value
            self._names.setdefault(mname, []).append((path, node.lineno))
            if path not in _METRIC_MODULES:
                yield (node.lineno,
                       f"metric '{mname}' declared outside a metrics "
                       "module; declare it in runtime/metrics.py or "
                       "telemetry/*")
            for label in self._labels(node):
                if label not in _LABEL_ALLOWLIST:
                    yield (node.lineno,
                           f"metric '{mname}' label key '{label}' is "
                           "outside the bounded allowlist "
                           "(analysis/rules.py _LABEL_ALLOWLIST); new keys "
                           "are a cardinality decision — add deliberately")

    def _labels(self, node: ast.Call) -> List[str]:
        cands = []
        if len(node.args) >= 3:
            cands.append(node.args[2])
        for kw in node.keywords:
            if kw.arg == "labelnames":
                cands.append(kw.value)
        out = []
        for c in cands:
            if isinstance(c, (ast.List, ast.Tuple)):
                for e in c.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        out.append(e.value)
        return out

    def finalize(self) -> List[Finding]:
        out = []
        for mname, sites in self._names.items():
            if len(sites) > 1:
                first = sites[0]
                for path, line in sites[1:]:
                    out.append(Finding(
                        self.id, path, line,
                        f"metric '{mname}' already declared at "
                        f"{first[0]}:{first[1]}; duplicate declarations "
                        "stack collectors on re-import"))
        return out


@register
class NoUnboundedBlocking(Rule):
    """R008: a reconcile body must never block without a bound —
    ``time.sleep`` (requeue with delay instead), ``.acquire()`` /
    ``.wait()`` / ``.join()`` with no timeout.  One stuck worker pins its
    key forever and eats a queue slot; the watchdog can dump it but not
    unstick it."""

    id = "R008"
    summary = ("no unbounded blocking (sleep, acquire/wait/join sans "
               "timeout) inside reconcile bodies")
    scope = (CONTROLLERS, RUNTIME)

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = node.name
            if not (name == "reconcile" or name.startswith("reconcile_")
                    or name.startswith("_reconcile")):
                continue
            yield from self._scan(node)

    def _scan(self, func) -> Iterable[Tuple[int, str]]:
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            chain = (_attr_chain(node.func)
                     if isinstance(node.func, ast.Attribute) else None)
            if chain == ["time", "sleep"]:
                yield (node.lineno,
                       "time.sleep in a reconcile body; return a requeue "
                       "delay instead (the workqueue owns time)")
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            if attr not in ("acquire", "wait", "join"):
                continue
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
            if attr == "acquire":
                # acquire(False) / acquire(blocking=False) cannot block.
                nonblocking = (
                    (node.args
                     and isinstance(node.args[0], ast.Constant)
                     and node.args[0].value is False)
                    or any(kw.arg == "blocking"
                           and isinstance(kw.value, ast.Constant)
                           and kw.value.value is False
                           for kw in node.keywords)
                    or (len(node.args) >= 2))  # positional timeout
                if has_timeout or nonblocking:
                    continue
            else:
                if has_timeout or node.args:
                    continue
            yield (node.lineno,
                   f".{attr}() without a timeout inside a reconcile body "
                   "can block a worker forever; pass timeout= and handle "
                   "the miss")


@register
class StampedChildCreates(Rule):
    """R009: child-object creates in controllers go through the
    context-stamping ``runtime.apply`` helpers (``apply.create`` /
    ``create_or_update``) — a raw ``client.create`` drops the
    ``kubeflow.org/traceparent`` annotation and severs the object
    journey SILENTLY: the child converges fine, but its watch events,
    reconciles and write RTTs vanish from `/debug/journey` and the
    critical-path decomposition under-reports forever.  Scope: the
    INJECTED client only (``self.client`` / bare ``client``) — creates
    on any other client-shaped receiver are already R001 fence-bypass
    findings, and the two rules never double-report one site."""

    id = "R009"
    summary = ("controller child creates go through the context-stamping "
               "apply.create / create_or_update, never raw client.create")
    scope = (CONTROLLERS,)

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "create"):
                continue
            chain = _attr_chain(fn.value)
            if chain in (["self", "client"], ["client"]):
                yield (node.lineno,
                       f"raw {'.'.join(chain)}.create() drops the "
                       "traceparent annotation and severs the child's "
                       "journey; use apply.create(self.client, obj) or "
                       "apply.create_or_update")


@register
class CodecSeamDecode(Rule):
    """R010: watch/list hot-path JSON decode routes through the
    ``k8s.codec`` seam (``decode_event`` / ``materialize``) — a raw
    ``json.loads`` in runtime/ or k8s/ re-opens the Python byte wall the
    native wire fast path removed (ISSUE 18): the event pays a full
    document parse again, invisibly to the codec engine counters and the
    ``ctrlplane_events_decoded_per_s`` band, and skips the LazyResource
    deferral that keeps non-admitted replicas from decoding bodies at
    all.  The seam modules themselves (codec.py, and client.py for raw
    error/Status bodies at the transport edge) are the sanctioned homes
    for the real parses."""

    id = "R010"
    summary = ("watch/list hot-path JSON decode goes through k8s.codec "
               "(decode_event/materialize), never raw json.loads")
    scope = (RUNTIME, "kubeflow_tpu/platform/k8s/*.py")
    exclude = (
        "kubeflow_tpu/platform/k8s/codec.py",   # the seam itself
        "kubeflow_tpu/platform/k8s/client.py",  # transport-edge bodies
    )

    def check(self, tree, text, path):
        for node in ast.walk(tree):
            # `from json import loads` aliases the parser out from under
            # the receiver check — flag the import itself (R005 pattern).
            if (isinstance(node, ast.ImportFrom) and node.module == "json"
                    and any(a.name in ("loads", "load")
                            for a in node.names)):
                yield (node.lineno,
                       "importing loads/load from json hides hot-path "
                       "decodes from the codec seam; route through "
                       "codec.decode_event / codec.materialize")
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("loads", "load")
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id == "json"):
                yield (node.lineno,
                       "raw json." + node.func.attr + "() on the "
                       "watch/list hot path bypasses the codec seam; use "
                       "codec.decode_event / codec.materialize (native "
                       "fast path, engine counters, lazy bodies)")
