"""Checkpoint/resume for sharded train states (Orbax-backed).

The reference platform's checkpoint story is PVC persistence + stop/start
annotations (SURVEY.md §5 "checkpoint/resume" — no model checkpointing, it
has no models).  The TPU framework adds the model half: async Orbax
checkpoints of the full TrainState, restored *directly into the mesh
sharding* (each host reads only its shard — no host-RAM blowup on multi-host
slices), with best-k retention and resume-from-latest.

    mgr = CheckpointManager(dir, max_to_keep=3)
    mgr.save(step, state)                   # async, non-blocking
    state = mgr.restore(state_template)     # template carries shardings
"""
from __future__ import annotations

from typing import Optional

import jax

from kubeflow_tpu.train.steps import TrainState


def _as_pytree(state: TrainState) -> dict:
    """The savable part of a TrainState (tx/apply_fn are code, not data)."""
    tree = {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }
    if state.batch_stats is not None:
        tree["batch_stats"] = state.batch_stats
    return tree


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager for TrainStates."""

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = directory
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        # An explicit handler lets a fresh (read-only) manager resolve
        # item_metadata without having performed a save/restore first, and
        # the PyTree handler (the layer Standard* wraps, same on-disk
        # format) additionally accepts PLACEHOLDER targets — both needed by
        # restore_params.
        self._mgr = ocp.CheckpointManager(
            directory, options=options,
            item_handlers=ocp.PyTreeCheckpointHandler(),
        )

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        """Queue an async save; returns False if skipped by save_interval."""
        return self._mgr.save(
            int(step),
            args=self._ocp.args.PyTreeSave(_as_pytree(state)),
            force=force,
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(
        self, template: TrainState, *, step: Optional[int] = None
    ) -> Optional[TrainState]:
        """Restore into the shardings/dtypes of ``template``.

        ``template`` is a fully-built (possibly freshly-initialized and
        mesh-sharded) TrainState; restored arrays land with the template
        leaves' shardings.  Returns None when no checkpoint exists —
        callers start from scratch (the resume-or-init idiom).
        """
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            if isinstance(x, jax.Array)
            else x,
            _as_pytree(template),
        )
        restored = self._mgr.restore(
            int(step),
            args=self._ocp.args.PyTreeRestore(
                abstract, restore_args=self._restore_args(abstract)
            ),
        )
        return template.replace(
            step=restored["step"],
            params=restored["params"],
            opt_state=restored["opt_state"],
            batch_stats=restored.get("batch_stats", template.batch_stats),
        )

    def restore_params(self, *, step: Optional[int] = None, template=None):
        """Restore only the params subtree, without needing the training
        optimizer to rebuild the full TrainState template — the serving
        path (models/serve.py) reads checkpoints written by any optimizer.
        Non-params subtrees (opt_state can be 2x params for Adam) are
        PLACEHOLDER'd so they are neither read from disk nor held in RAM.
        Returns None when no checkpoint exists.

        ``template``: optional abstract params pytree (shape/dtype, and
        optionally sharding) — leaves restore directly into that
        dtype/placement.  SPMD serving passes mesh-sharded leaves here so
        a model larger than one device's HBM never materializes
        replicated."""
        import jax

        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        meta = self._mgr.item_metadata(int(step))
        tree = getattr(meta, "tree", None) or meta
        if template is not None:
            params_target = jax.tree.map(
                lambda t: jax.ShapeDtypeStruct(
                    t.shape, t.dtype,
                    sharding=getattr(t, "sharding", None),
                ),
                template,
            )
        else:
            params_target = jax.tree.map(
                lambda n: jax.ShapeDtypeStruct(n.shape, n.dtype),
                tree["params"],
            )
        placeholder = getattr(self._ocp, "PLACEHOLDER", None)
        if placeholder is not None:
            # Newer Orbax: non-params subtrees PLACEHOLDER'd in a
            # full-structure target.
            target = {
                key: params_target if key == "params"
                else jax.tree.map(lambda _n: placeholder, sub)
                for key, sub in tree.items()
            }
            restore_kwargs = {}
        else:
            # Older Orbax has no PLACEHOLDER sentinel; its partial-restore
            # spelling is a params-only target plus ``transforms={}`` —
            # checkpoint keys absent from the target are then "implicitly
            # ignored, and not restored" (PyTreeCheckpointHandler restore
            # rule 5), which keeps the skip-the-opt-state property: those
            # subtrees are neither read from disk nor held in RAM.
            target = {"params": params_target}
            restore_kwargs = {"transforms": {}}
        restored = self._mgr.restore(
            int(step),
            args=self._ocp.args.PyTreeRestore(
                target, restore_args=self._restore_args(target),
                **restore_kwargs,
            ),
        )
        return restored["params"]

    def _restore_args(self, target):
        """Per-leaf RestoreArgs: THIS is where Orbax honors shardings — a
        plain ShapeDtypeStruct.sharding is silently ignored by the
        installed version (arrays land replicated on device 0; probed
        directly), so every sharded leaf gets an ArrayRestoreArgs."""

        def one(node):
            sharding = getattr(node, "sharding", None)
            if sharding is not None:
                return self._ocp.ArrayRestoreArgs(
                    sharding=sharding, dtype=node.dtype
                )
            dtype = getattr(node, "dtype", None)
            if dtype is not None:
                # Unsharded leaves still restore in the TEMPLATE dtype: a
                # checkpoint saved in another dtype must not leak its
                # on-disk dtype into the serving model.
                return self._ocp.RestoreArgs(dtype=dtype)
            return self._ocp.RestoreArgs()

        return jax.tree.map(one, target)

    def wait(self) -> None:
        """Block until queued async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        self.close()
