"""JAX profiler capture for notebook workloads.

SURVEY.md §5 notes the reference has no tracing story at all; here the
compute stack exposes one that plugs into the platform: traces land in a
logdir a Tensorboard CR can point at (``pvc://.../profile``), so "profile
my training loop" is ``with profile_trace(logdir): run_steps()`` followed
by opening the TensorBoard the tensorboards web app already serves.

Two entry points:

* ``profile_trace(logdir)`` — context manager around a region; captures
  XLA device traces (TPU timeline, HLO op breakdown in TensorBoard's
  profile plugin).
* ``profile_steps(logdir, step_fn, *args, warmup, steps)`` — the common
  notebook move: warm up (compile excluded), then trace N steps.
"""
from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Tuple

import jax


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture a JAX profiler trace for the enclosed region."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def profile_steps(
    logdir: str,
    step_fn: Callable,
    *args: Any,
    warmup: int = 2,
    steps: int = 5,
) -> Tuple[Any, str]:
    """Trace ``steps`` invocations of ``step_fn(*args)`` after ``warmup``
    untraced ones (compile + autotuning excluded from the trace).  The
    step's first argument is treated as loop-carried state when the step
    returns ``(state, metrics)``; otherwise outputs are discarded and the
    same args repeat.  Returns (last output, trace directory)."""
    out = None

    def once(current_args):
        result = step_fn(*current_args)
        if (
            isinstance(result, tuple)
            and len(result) == 2
            and current_args
            and jax.tree_util.tree_structure(result[0])
            == jax.tree_util.tree_structure(current_args[0])
        ):
            return result, (result[0], *current_args[1:])
        return result, current_args

    current = tuple(args)
    for _ in range(warmup):
        out, current = once(current)
    _block(out)
    with profile_trace(logdir):
        for _ in range(steps):
            out, current = once(current)
        _block(out)
    return out, logdir


def _block(out: Any) -> None:
    for leaf in jax.tree_util.tree_leaves(out):
        if isinstance(leaf, jax.Array):
            leaf.block_until_ready()
