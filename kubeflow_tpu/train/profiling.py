"""JAX profiler capture for notebook workloads.

SURVEY.md §5 notes the reference has no tracing story at all; here the
compute stack exposes one that plugs into the platform: traces land in a
logdir a Tensorboard CR can point at (``pvc://.../profile``), so "profile
my training loop" is ``with profile_trace(logdir): run_steps()`` followed
by opening the TensorBoard the tensorboards web app already serves.

Two entry points:

* ``profile_trace(logdir)`` — context manager around a region; captures
  XLA device traces (TPU timeline, HLO op breakdown in TensorBoard's
  profile plugin).
* ``profile_steps(logdir, step_fn, *args, warmup, steps)`` — the common
  notebook move: warm up (compile excluded), then trace N steps.
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Any, Callable, Tuple

import jax

log = logging.getLogger("kubeflow_tpu.train.profiling")


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture a JAX profiler trace for the enclosed region.

    Crash-safe: when the REGION raises, ``stop_trace`` runs on a
    best-effort basis — it can itself raise (e.g. ``start_trace`` died
    half-initialized, or the backend wedged with the region), and a
    profiling cleanup error must never mask the training exception the
    operator actually needs.  On the clean path a ``stop_trace`` failure
    still propagates: a "successful" profile with no trace written would
    be a silent lie."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except Exception:
            log.warning(
                "profiler stop_trace failed while unwinding a crashed "
                "region (trace under %s may be incomplete)", logdir,
                exc_info=True,
            )
        raise
    else:
        jax.profiler.stop_trace()


def profile_steps(
    logdir: str,
    step_fn: Callable,
    *args: Any,
    warmup: int = 2,
    steps: int = 5,
) -> Tuple[Any, str]:
    """Trace ``steps`` invocations of ``step_fn(*args)`` after ``warmup``
    untraced ones (compile + autotuning excluded from the trace).  The
    step's first argument is treated as loop-carried state when the step
    returns ``(state, metrics)``; otherwise outputs are discarded and the
    same args repeat.  Returns (last output, trace directory)."""
    out = None

    def once(current_args):
        result = step_fn(*current_args)
        if (
            isinstance(result, tuple)
            and len(result) == 2
            and current_args
            and jax.tree_util.tree_structure(result[0])
            == jax.tree_util.tree_structure(current_args[0])
        ):
            return result, (result[0], *current_args[1:])
        return result, current_args

    current = tuple(args)
    for _ in range(warmup):
        out, current = once(current)
    _block(out)
    with profile_trace(logdir):
        for _ in range(steps):
            out, current = once(current)
        _block(out)
    return out, logdir


def _block(out: Any) -> None:
    for leaf in jax.tree_util.tree_leaves(out):
        if isinstance(leaf, jax.Array):
            leaf.block_until_ready()


def trace_summary(logdir: str) -> dict:
    """Aggregate the newest device trace under ``logdir`` by HLO category.

    Parses the Chrome-trace JSON the profiler writes (each XLA-op event
    carries ``hlo_category``, ``bytes_accessed`` and ``model_flops``) and
    returns, per category: total device milliseconds, gigabytes accessed,
    and the achieved GB/s / TF/s — the inputs to a roofline argument.
    Host-side events are excluded; only ``/device:*`` "XLA Ops" rows count.
    """
    import collections
    import glob
    import gzip
    import json

    traces = sorted(
        glob.glob(
            os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz")
        )
    )
    if not traces:
        raise FileNotFoundError(f"no trace.json.gz under {logdir}")
    with gzip.open(traces[-1]) as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    pids, tids = {}, {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"]["name"]
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tids[(e["pid"], e["tid"])] = e["args"]["name"]
    agg = collections.defaultdict(lambda: [0.0, 0, 0])  # us, bytes, flops
    for e in events:
        if (
            e.get("ph") != "X"
            or not pids.get(e.get("pid"), "").startswith("/device:")
            or tids.get((e.get("pid"), e.get("tid"))) != "XLA Ops"
            or "args" not in e
        ):
            continue
        a = e["args"]
        cat = a.get("hlo_category", "other")
        if cat.endswith("-start"):
            # async-start/copy-start carry the transfer's bytes with ~zero
            # duration; the device time AND the same bytes appear again on
            # the paired -done event — counting both double-books traffic.
            continue
        row = agg[cat]
        row[0] += float(e.get("dur", 0.0) or 0.0)
        row[1] += int(a.get("bytes_accessed", 0) or 0)
        row[2] += int(a.get("model_flops", 0) or 0)
    if not agg:
        raise ValueError(
            f"trace under {logdir} has no device-side XLA-op events "
            "(non-TPU backend?); refusing to report a zero profile"
        )
    categories = {}
    for cat, (us, byt, fl) in agg.items():
        sec = us / 1e6
        categories[cat] = {
            "ms": us / 1e3,
            "gb": byt / 1e9,
            "gb_per_s": byt / sec / 1e9 if sec else 0.0,
            "tf_per_s": fl / sec / 1e12 if sec else 0.0,
        }
    return {
        "total_ms": sum(v[0] for v in agg.values()) / 1e3,
        "total_gb": sum(v[1] for v in agg.values()) / 1e9,
        "total_tf": sum(v[2] for v in agg.values()) / 1e12,
        "categories": dict(
            sorted(categories.items(), key=lambda kv: -kv[1]["ms"])
        ),
    }
