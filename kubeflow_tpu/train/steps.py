"""Jit-able train steps.

Design: a step is a pure function ``(state, batch, rng) -> (state, metrics)``
built once by a factory and then wrapped by the caller in ``jax.jit`` with
whatever shardings apply (see kubeflow_tpu.parallel.train).  No
data-dependent Python control flow — everything traces once.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any  # None for stat-less models
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    apply_fn: Callable = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def create_train_state(
    rng: jax.Array,
    model,
    example_input,
    tx: optax.GradientTransformation,
    *,
    init_kwargs: Optional[dict] = None,
) -> TrainState:
    variables = model.init(rng, example_input, **(init_kwargs or {}))
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=batch_stats,
        tx=tx,
        apply_fn=model.apply,
    )


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels, f32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_classification_train_step(*, has_batch_stats: bool, has_dropout: bool = False):
    """Step for image/sequence classifiers: batch = (inputs, int labels)."""

    def step(state: TrainState, batch, rng: Optional[jax.Array] = None):
        inputs, labels = batch

        def loss_fn(params):
            variables = {"params": params}
            kwargs: dict = {"train": True}
            # mutable must be False (not []) when nothing is collected:
            # flax returns an (out, vars) tuple for ANY non-False mutable.
            mutable = ["batch_stats"] if has_batch_stats else False
            if has_batch_stats:
                variables["batch_stats"] = state.batch_stats
            if has_dropout:
                kwargs["rngs"] = {"dropout": rng}
            out = state.apply_fn(variables, inputs, mutable=mutable, **kwargs)
            logits, new_model_state = out if mutable else (out, {})
            loss = cross_entropy(logits, labels)
            acc = jnp.mean(jnp.argmax(logits, -1) == labels)
            return loss, (new_model_state, acc)

        (loss, (new_model_state, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        state = state.apply_gradients(grads)
        if has_batch_stats:
            state = state.replace(batch_stats=new_model_state["batch_stats"])
        return state, {"loss": loss, "accuracy": acc}

    return step


def make_lm_train_step(*, aux_loss_weight: float = 0.0):
    """Next-token-prediction step: batch = tokens[b,s] or (tokens, segment_ids)
    for packed sequences (segment_ids are threaded into attention masking).

    ``aux_loss_weight`` > 0 collects the ``"losses"`` collection sowed by MoE
    layers (``moe_aux_loss``) and adds the weighted sum to the objective.
    """

    def step(state: TrainState, batch, rng: Optional[jax.Array] = None):
        if isinstance(batch, (tuple, list)):
            tokens = batch[0]
            segment_ids = batch[1] if len(batch) > 1 else None
        else:
            tokens, segment_ids = batch, None

        def loss_fn(params):
            kwargs = {} if segment_ids is None else {"segment_ids": segment_ids}
            if aux_loss_weight:
                logits, cols = state.apply_fn(
                    {"params": params}, tokens, mutable=["losses"], **kwargs
                )
                sowed = jax.tree.leaves(cols.get("losses", {}))
                # Mean per leaf, then mean over leaves: a python-loop model
                # sows n_layers scalar leaves; under scan_layers they arrive
                # as ONE stacked (n_layers,) leaf — both reduce to the same
                # scalar mean-over-layers.
                aux = (
                    sum(jnp.mean(x) for x in sowed) / max(1, len(sowed))
                    if sowed else 0.0
                )
            else:
                logits = state.apply_fn({"params": params}, tokens, **kwargs)
                aux = 0.0
            # Shift: predict token t+1 from prefix..t.
            logits = logits[:, :-1]
            targets = tokens[:, 1:]
            loss = cross_entropy(logits, targets)
            return loss + aux_loss_weight * aux, (loss, aux)

        (total, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        state = state.apply_gradients(grads)
        metrics = {"loss": loss}
        if aux_loss_weight:
            metrics["moe_aux_loss"] = aux
        return state, metrics

    return step
