"""Jit-able train steps.

Design: a step is a pure function ``(state, batch, rng) -> (state, metrics)``
built once by a factory and then wrapped by the caller in ``jax.jit`` with
whatever shardings apply (see kubeflow_tpu.parallel.train).  No
data-dependent Python control flow — everything traces once.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any  # None for stat-less models
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)
    apply_fn: Callable = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        # Mixed precision (grad_dtype=bf16): upcast stored grads to the
        # param dtype at the point of use — XLA fuses the cast into the
        # update's elementwise pass, so no f32 gradient buffer ever
        # materializes, but the optimizer math runs at master precision.
        grads = jax.tree.map(
            lambda g, p: g.astype(p.dtype)
            if hasattr(g, "dtype") and g.dtype != p.dtype else g,
            grads, self.params,
        )
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def create_train_state(
    rng: jax.Array,
    model,
    example_input,
    tx: optax.GradientTransformation,
    *,
    init_kwargs: Optional[dict] = None,
) -> TrainState:
    # model.init runs a full forward — op by op when called eagerly, which
    # materializes EVERY intermediate at once at full sequence length (the
    # exact frame BENCH_r05 died in with RESOURCE_EXHAUSTED at seq 8192).
    # Tracing it under jit instead lets XLA fuse the iota-comparison
    # attention masks (ops/attention.py) and free layer intermediates as
    # it schedules, so train-state creation never holds O(S²) buffers
    # op-by-op.  init_kwargs are bound via partial so static flags like
    # train=False stay Python values.
    init_fn = model.init
    if init_kwargs:
        init_fn = functools.partial(init_fn, **init_kwargs)
    variables = jax.jit(init_fn)(rng, example_input)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=batch_stats,
        tx=tx,
        apply_fn=model.apply,
    )


@jax.custom_vjp
def _token_nll(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-token negative log-likelihood [..., ] from f32 logits [..., V].

    Custom VJP so the forward never materializes log_softmax over the
    vocabulary: standard AD saves the full [batch, seq, vocab] f32
    log-probs as a residual — at seq 8192 / vocab 8192 that is a 536 MB
    tensor whose transposed-layout write alone took 54.5 ms/step, 32% of
    the llama-8k flash train step (round-3 profile, BASELINE.md).  Here
    the forward reduces on the fly (max + logsumexp, [batch, seq]
    residuals only) and the backward recomputes softmax fused directly
    into d_logits = (probs - onehot) * g — one vocab-sized write, which
    the lm_head gradient matmul needs anyway.
    """
    return _token_nll_fwd(logits, labels)[0]


def _token_nll_fwd(logits, labels):
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1)
    )
    ll = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    # logits are the live lm_head output — saving them adds no copy.
    return lse - ll, (logits, labels, lse)


def _token_nll_bwd(res, g):
    logits, labels, lse = res
    probs = jnp.exp(logits - lse[..., None])
    d = probs - jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
    return d * g[..., None], None


_token_nll.defvjp(_token_nll_fwd, _token_nll_bwd)


def cross_entropy(
    logits: jax.Array, labels: jax.Array,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean softmax cross-entropy over integer labels, f32.  ``weights``
    (same shape as labels) turns it into a weighted mean — the packed-
    sequence path zeroes pad and cross-document targets."""
    nll = _token_nll(logits.astype(jnp.float32), labels)
    if weights is None:
        return jnp.mean(nll)
    w = weights.astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def make_classification_grad_fn(*, has_batch_stats: bool, has_dropout: bool = False):
    """(state, batch, rng) → (grads, new_model_state, metrics) for image/
    sequence classifiers: batch = (inputs, int labels)."""

    def grad_fn(state: TrainState, batch, rng: Optional[jax.Array] = None):
        inputs, labels = batch

        def loss_fn(params):
            variables = {"params": params}
            kwargs: dict = {"train": True}
            # mutable must be False (not []) when nothing is collected:
            # flax returns an (out, vars) tuple for ANY non-False mutable.
            mutable = ["batch_stats"] if has_batch_stats else False
            if has_batch_stats:
                variables["batch_stats"] = state.batch_stats
            if has_dropout:
                kwargs["rngs"] = {"dropout": rng}
            out = state.apply_fn(variables, inputs, mutable=mutable, **kwargs)
            logits, new_model_state = out if mutable else (out, {})
            loss = cross_entropy(logits, labels)
            acc = jnp.mean(jnp.argmax(logits, -1) == labels)
            return loss, (new_model_state, acc)

        (loss, (new_model_state, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        return grads, new_model_state, {"loss": loss, "accuracy": acc}

    return grad_fn


def make_classification_train_step(*, has_batch_stats: bool, has_dropout: bool = False):
    """Step for image/sequence classifiers: batch = (inputs, int labels)."""
    grad_fn = make_classification_grad_fn(
        has_batch_stats=has_batch_stats, has_dropout=has_dropout
    )

    def step(state: TrainState, batch, rng: Optional[jax.Array] = None):
        grads, new_model_state, metrics = grad_fn(state, batch, rng)
        state = state.apply_gradients(grads)
        if has_batch_stats:
            state = state.replace(batch_stats=new_model_state["batch_stats"])
        return state, metrics

    return step


def chunked_cross_entropy(
    hidden: jax.Array,
    head_kernel: jax.Array,
    labels: jax.Array,
    weights: Optional[jax.Array] = None,
    *,
    chunk: int = 1024,
) -> jax.Array:
    """Mean next-token cross-entropy WITHOUT materializing full logits.

    ``hidden`` [B, S, D] (post-final-norm, pre-head), ``head_kernel``
    [D, V], ``labels`` [B, S].  A ``lax.scan`` over sequence chunks
    applies the lm_head and the fused token-NLL per chunk under
    ``jax.checkpoint``, so peak vocab-sized residency is one
    [B, chunk, V] tile in each direction instead of [B, S, V] — at
    1.36B/seq 32k the full f32 logits alone are 4.2 GB, more than the
    chip has left.  The backward recomputes each chunk's head matmul
    (2·d·vocab per token ≈ 1-2% extra model FLOPs); dW accumulates
    across chunks through the scan's closure-gradient sum.  Values and
    gradients match the unchunked ``cross_entropy`` path to bf16/f32
    tolerance (tests/test_train_loop.py)."""
    b, s, d = hidden.shape
    if s % chunk:
        raise ValueError(f"seq len {s} not divisible by ce chunk {chunk}")
    n = s // chunk
    h = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n, B, C, D]
    y = labels.reshape(b, n, chunk).swapaxes(0, 1)     # [n, B, C]
    if weights is None:
        w = jnp.ones((n, b, chunk), jnp.float32)
    else:
        w = weights.astype(jnp.float32).reshape(b, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def body(carry, xs):
        h_c, y_c, w_c = xs
        # Same math as the unchunked head: nn.Dense(dtype=f32) casts the
        # bf16 activations up and multiplies against the f32 kernel.
        logits = jnp.einsum(
            "bcd,dv->bcv", h_c.astype(jnp.float32),
            head_kernel.astype(jnp.float32))
        nll = _token_nll(logits, y_c)
        loss_sum, w_sum = carry
        return (loss_sum + jnp.sum(nll * w_c), w_sum + jnp.sum(w_c)), None

    (loss_sum, w_sum), _ = jax.lax.scan(body, (0.0, 0.0), (h, y, w))
    return loss_sum / jnp.maximum(w_sum, 1.0)


def make_lm_grad_fn(*, aux_loss_weight: float = 0.0,
                    grad_dtype: Optional[Any] = None,
                    ce_chunk: Optional[int] = None):
    """(state, batch, rng) → (grads, new_model_state, metrics) for
    next-token prediction; see make_lm_train_step for batch forms.

    ``grad_dtype`` (e.g. ``jnp.bfloat16``): cast floating params to this
    dtype BEFORE differentiation so the materialized per-parameter
    gradients come back in it — the standard mixed-precision recipe
    (bf16 grads + f32 master weights updated by the optimizer).  At 1.36B
    params this halves gradient memory (5.46 → 2.73 GB), which is what
    lets batch 2 / seq 16k compile on a 16 GB chip (BASELINE.md "1.36B
    context-scaling boundary").  The model already computes in its
    config dtype either way; only the gradient STORAGE changes.  Loss of
    gradient precision is the bf16 mantissa (8 bits) — fine for SGD/Adam
    at LLM scale (what large runs ship); pinned within tolerance vs f32
    grads by tests/test_train_loop.py."""

    def _cast_params(params):
        if grad_dtype is None:
            return params
        return jax.tree.map(
            lambda x: x.astype(grad_dtype)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
            params,
        )

    def grad_fn(state: TrainState, batch, rng: Optional[jax.Array] = None):
        if isinstance(batch, (tuple, list)):
            tokens = batch[0]
            segment_ids = batch[1] if len(batch) > 1 else None
        else:
            tokens, segment_ids = batch, None

        def loss_fn(params):
            kwargs = {} if segment_ids is None else {"segment_ids": segment_ids}
            if ce_chunk is not None:
                kwargs["return_hidden"] = True
            if aux_loss_weight:
                out, cols = state.apply_fn(
                    {"params": params}, tokens, mutable=["losses"], **kwargs
                )
                sowed = jax.tree.leaves(cols.get("losses", {}))
                # Mean per leaf, then mean over leaves: a python-loop model
                # sows n_layers scalar leaves; under scan_layers they arrive
                # as ONE stacked (n_layers,) leaf — both reduce to the same
                # scalar mean-over-layers.
                aux = (
                    sum(jnp.mean(x) for x in sowed) / max(1, len(sowed))
                    if sowed else 0.0
                )
            else:
                out = state.apply_fn({"params": params}, tokens, **kwargs)
                aux = 0.0
            # Next-token targets: predict token t+1 from prefix..t.  The
            # packed-row weights (data/packing.py) count a target only
            # when it continues the SAME document and is not a pad slot.
            shifted_valid = None
            if segment_ids is not None:
                shifted_valid = (
                    (segment_ids[:, 1:] == segment_ids[:, :-1])
                    & (segment_ids[:, 1:] != 0)
                )
            if ce_chunk is not None:
                # Chunked head+CE over the FULL length (the chunk grid
                # needs S % chunk == 0, which a [:, :-1] shift breaks):
                # targets are tokens rolled left, with the wrapped final
                # position weighted 0 — identical math to the shifted
                # unchunked path.
                hidden = out
                b, s = tokens.shape
                targets = jnp.concatenate(
                    [tokens[:, 1:], tokens[:, :1]], axis=1)
                valid = (jnp.ones((b, s - 1), jnp.float32)
                         if shifted_valid is None
                         else shifted_valid.astype(jnp.float32))
                w = jnp.concatenate(
                    [valid, jnp.zeros((b, 1), jnp.float32)], axis=1)
                loss = chunked_cross_entropy(
                    hidden, params["lm_head"]["kernel"], targets, w,
                    chunk=ce_chunk)
            else:
                logits = out[:, :-1]
                targets = tokens[:, 1:]
                loss = cross_entropy(logits, targets, weights=shifted_valid)
            return loss + aux_loss_weight * aux, (loss, aux)

        (total, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            _cast_params(state.params)
        )
        metrics = {"loss": loss}
        if aux_loss_weight:
            metrics["moe_aux_loss"] = aux
        return grads, {}, metrics

    return grad_fn


def make_lm_train_step(*, aux_loss_weight: float = 0.0,
                       grad_dtype: Optional[Any] = None,
                       ce_chunk: Optional[int] = None):
    """Next-token-prediction step: batch = tokens[b,s] or (tokens, segment_ids)
    for packed sequences (segment_ids are threaded into attention masking).

    ``aux_loss_weight`` > 0 collects the ``"losses"`` collection sowed by MoE
    layers (``moe_aux_loss``) and adds the weighted sum to the objective.
    ``grad_dtype``: see make_lm_grad_fn (bf16 grads + f32 master weights).
    ``ce_chunk``: chunked lm_head + cross-entropy (chunked_cross_entropy) —
    the long-context memory lever; requires a model supporting
    ``return_hidden=True`` with an ``lm_head`` Dense (models/llama.py).
    """
    grad_fn = make_lm_grad_fn(aux_loss_weight=aux_loss_weight,
                              grad_dtype=grad_dtype, ce_chunk=ce_chunk)

    def step(state: TrainState, batch, rng: Optional[jax.Array] = None):
        grads, _, metrics = grad_fn(state, batch, rng)
        state = state.apply_gradients(grads)
        return state, metrics

    return step


def make_grad_accum_step(
    grad_fn: Callable,
    n_accum: int,
    *,
    has_batch_stats: bool = False,
):
    """Accumulate gradients over ``n_accum`` microbatches inside ONE jitted
    step (``lax.scan``), then apply a single optimizer update.

    The batch's leading axis is split into ``n_accum`` equal microbatches,
    so the effective batch is the full input while peak activation memory is
    that of one microbatch — the standard trade when a model's optimal batch
    does not fit HBM.  Metrics are averaged over microbatches; with
    batch_stats the last microbatch's stats win (the usual convention — EMA
    stats converge regardless of which microbatch closes the step).
    """
    if n_accum < 1:
        raise ValueError(f"n_accum must be >= 1, got {n_accum}")

    def step(state: TrainState, batch, rng: Optional[jax.Array] = None):
        def split(x):
            if x.shape[0] % n_accum:
                raise ValueError(
                    f"batch axis {x.shape[0]} not divisible by n_accum {n_accum}"
                )
            return x.reshape((n_accum, x.shape[0] // n_accum) + x.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, mb_and_i):
            grads_acc, stats = carry
            mb, i = mb_and_i
            mb_rng = None if rng is None else jax.random.fold_in(rng, i)
            st = state if stats is None else state.replace(batch_stats=stats)
            grads, new_model_state, metrics = grad_fn(st, mb, mb_rng)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            new_stats = (
                new_model_state.get("batch_stats") if has_batch_stats else None
            )
            return (grads_acc, new_stats), metrics

        zero_grads = jax.tree.map(jnp.zeros_like, state.params)
        init = (zero_grads, state.batch_stats if has_batch_stats else None)
        (grads_sum, stats), metrics_seq = jax.lax.scan(
            body, init, (micro, jnp.arange(n_accum))
        )
        grads = jax.tree.map(lambda g: g / n_accum, grads_sum)
        state = state.apply_gradients(grads)
        if has_batch_stats:
            state = state.replace(batch_stats=stats)
        metrics = jax.tree.map(jnp.mean, metrics_seq)
        return state, metrics

    return step
