"""Trainer CLI: spawn-and-train in one command, SPMD over any mesh.

    python -m kubeflow_tpu.train.run --model llama_debug --task lm \\
        --steps 100 --batch 32 --seq 256 --mesh dp=2,fsdp=2,tp=2 \\
        --checkpoint-dir /workspace/ckpt

Reads TPU worker env injected by the platform (TPU_WORKER_ID etc. — see
parallel/dist.py) for multi-host bring-up, builds the mesh, shards the
train state by the model family's partition rules, and runs the shared
train loop with checkpoint/resume.  ``--mesh auto`` factorizes the device
count via ``default_mesh_config``.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import Optional

import jax

from kubeflow_tpu.parallel import envspec
from kubeflow_tpu.platform import config


def install_preemption_handler(stop: threading.Event,
                               signals=(signal.SIGTERM,)) -> bool:
    """Graceful-preemption hook: on SIGTERM (what a TPU preemption or a
    gang teardown delivers to the pod) set ``stop`` so the train loop
    exits between steps and its ``finally`` force-saves + waits on a
    checkpoint — the piece that makes the TPUJob controller's
    "restart resumes from latest_step()" honest on real preemptions.

    Returns False (and installs nothing) when not on the main thread —
    Python only delivers signals there, and library callers embedding the
    trainer in a worker thread handle termination themselves."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _handler(signum, frame):
        stop.set()

    for sig in signals:
        signal.signal(sig, _handler)
    return True


def parse_mesh(spec: str, n_devices: int):
    """'auto' or 'tp=4,fsdp=2' → Mesh.  Raises ValueError on a bad spec
    (library error contract — callers like serve.load_service handle it;
    the CLI surfaces it as a clean exit via main's argparse error)."""
    from kubeflow_tpu.parallel import default_mesh_config, make_mesh
    from kubeflow_tpu.parallel.mesh import MeshConfig

    if spec == "auto":
        return make_mesh(default_mesh_config(n_devices))
    axes = {}
    for part in spec.split(","):
        if not part:
            continue
        key, _, value = part.partition("=")
        if key not in MeshConfig.__dataclass_fields__:
            raise ValueError(f"unknown mesh axis {key!r} in {spec!r}")
        try:
            axes[key] = int(value)
        except ValueError:
            raise ValueError(
                f"mesh axis {key!r} needs an integer, got {value!r}"
            ) from None
    return make_mesh(**axes)


def build_lm(args, mesh):
    import jax.numpy as jnp

    from kubeflow_tpu.data.loader import ShardedLoader, synthetic_lm_batches
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.parallel import llama_rules
    from kubeflow_tpu.parallel.train import (
        make_sharded_train_step,
        shard_train_state,
    )
    from kubeflow_tpu.train import create_train_state, make_lm_train_step

    model = create_model(args.model, max_seq_len=args.seq)
    vocab = model.cfg.vocab_size
    import optax

    tokens = jnp.ones((args.batch, args.seq), jnp.int32)
    state = create_train_state(
        jax.random.key(args.seed), model, tokens, optax.adamw(args.lr)
    )
    state = shard_train_state(state, mesh, llama_rules())
    # Long-context memory levers (both measured in BASELINE.md): bf16
    # gradient storage with f32 master weights, and the chunked
    # lm_head+CE that keeps [B, S, vocab] logits from materializing.
    step_kwargs = {
        "grad_dtype": jnp.bfloat16 if args.grad_dtype == "bf16" else None,
        "ce_chunk": args.ce_chunk,
    }
    if args.grad_accum > 1:
        from kubeflow_tpu.train import make_grad_accum_step, make_lm_grad_fn

        pure_step = make_grad_accum_step(
            make_lm_grad_fn(**step_kwargs), args.grad_accum)
    else:
        pure_step = make_lm_train_step(**step_kwargs)
    step, data_sharding = make_sharded_train_step(
        pure_step, state, mesh, llama_rules()
    )
    def batches(start_step=0):
        if args.packed:
            # Packed documents (data/packing.py): padding-free rows with
            # segment ids; the packer's rolling window is stateful, so this
            # stream is NOT step-indexed — resume restarts the stream
            # (random synthetic data; real corpora should resume by shard).
            from kubeflow_tpu.data.loader import (
                _host_batch_size,
                synthetic_lm_documents,
            )
            from kubeflow_tpu.data.packing import packed_lm_batches

            max_len = min(256, args.seq)
            return ShardedLoader(
                packed_lm_batches(
                    synthetic_lm_documents(
                        vocab_size=vocab, seed=args.seed,
                        min_len=min(8, max_len), max_len=max_len,
                    ),
                    batch_rows=_host_batch_size(args.batch),
                    seq_len=args.seq,
                ),
                data_sharding,
            )
        # Step-indexed stream: resume replays exactly what an uninterrupted
        # run would have consumed from `start_step` on.
        return ShardedLoader(
            synthetic_lm_batches(
                global_batch=args.batch, seq_len=args.seq, vocab_size=vocab,
                seed=args.seed, start=start_step,
            ),
            data_sharding,
        )

    return state, step, batches


def build_image(args, mesh):
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.data.loader import ShardedLoader, synthetic_image_batches
    from kubeflow_tpu.models import create_model
    from kubeflow_tpu.parallel import resnet_rules
    from kubeflow_tpu.parallel.train import (
        make_sharded_train_step,
        shard_train_state,
    )
    from kubeflow_tpu.train import (
        create_train_state,
        make_classification_train_step,
    )

    model = create_model(args.model, num_classes=args.num_classes)
    images = jnp.ones((args.batch, args.image_size, args.image_size, 3),
                      jnp.float32)
    state = create_train_state(
        jax.random.key(args.seed), model, images,
        optax.sgd(args.lr, momentum=0.9), init_kwargs={"train": False},
    )
    state = shard_train_state(state, mesh, resnet_rules())
    if args.grad_accum > 1:
        from kubeflow_tpu.train import (
            make_classification_grad_fn,
            make_grad_accum_step,
        )

        pure_step = make_grad_accum_step(
            make_classification_grad_fn(has_batch_stats=True),
            args.grad_accum, has_batch_stats=True,
        )
    else:
        pure_step = make_classification_train_step(has_batch_stats=True)
    step, data_sharding = make_sharded_train_step(
        pure_step, state, mesh, resnet_rules(),
    )
    def batches(start_step=0):
        return ShardedLoader(
            synthetic_image_batches(
                global_batch=args.batch, image_size=args.image_size,
                num_classes=args.num_classes, seed=args.seed, start=start_step,
            ),
            data_sharding,
        )

    return state, step, batches


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="llama_debug")
    ap.add_argument("--task", choices=["lm", "image"], default="lm")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="microbatches accumulated per optimizer step "
                         "(scanned inside one jit; batch must divide evenly)")
    ap.add_argument("--grad-dtype", choices=["f32", "bf16"], default="f32",
                    help="lm task: gradient storage dtype; bf16 = mixed "
                         "precision with f32 master weights (halves grad "
                         "memory; under --grad-accum only the per-"
                         "microbatch grads shrink — the accumulator stays "
                         "f32 for summation precision)")
    ap.add_argument("--ce-chunk", type=int, default=None,
                    help="lm task: chunked lm_head+cross-entropy chunk "
                         "size (long-context memory lever; seq must "
                         "divide by it)")
    ap.add_argument("--packed", action="store_true",
                    help="lm task: pack variable-length documents into "
                         "padding-free rows with segment ids")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mesh", default="auto")
    # KFT_CHECKPOINT_DIR is the TPUJob controller's injection path
    # (parallel/envspec.py): a gang worker resumes from the job's stable
    # checkpoint dir without the image's command line knowing about it.
    ap.add_argument(
        "--checkpoint-dir",
        default=config.knob(
            envspec.ENV_KFT_CHECKPOINT_DIR, None,
            doc="checkpoint dir injected by the TPUJob controller") or None)
    ap.add_argument("--checkpoint-every", type=int, default=100)
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--distributed", action="store_true",
                    help="jax.distributed.initialize from platform-injected env")
    args = ap.parse_args(argv)

    if args.task == "image" and (args.ce_chunk is not None
                                 or args.grad_dtype != "f32"):
        # Loud, not silent: a user expecting the memory levers on the
        # image task would otherwise just OOM with no hint.
        ap.error("--grad-dtype/--ce-chunk apply to the lm task only")

    if args.distributed:
        from kubeflow_tpu.parallel.dist import elastic_slices, initialize_from_env

        initialize_from_env()
        allocated, declared = elastic_slices()
        if allocated < declared:
            # Elastic TPUJob gang running shrunk: the queue granted fewer
            # slices than spec.tpu.slices — same checkpoint, smaller
            # dcn(dp) axis; the controller grows the gang back when
            # capacity frees (docs/jobs.md).
            print(f"elastic: running at {allocated}/{declared} slices "
                  "(shrunk; will grow back via checkpoint-restart)",
                  flush=True)

    from kubeflow_tpu.parallel.context import global_mesh
    from kubeflow_tpu.train.loop import LoopConfig, train_loop

    try:
        mesh = parse_mesh(args.mesh, len(jax.devices()))
    except ValueError as e:
        ap.error(str(e))  # clean CLI exit, not a traceback
    print(f"devices={len(jax.devices())} mesh={dict(mesh.shape)}", flush=True)

    build = build_lm if args.task == "lm" else build_image
    telemetry_kwargs = {}
    if args.task == "lm":
        # Wire the loop's tokens/s + MFU gauges with the shared accounting
        # (telemetry.compute — the formula bench.py prints); the model
        # built here is a paramless config probe, not a second init.
        from kubeflow_tpu.models import create_model
        from kubeflow_tpu.telemetry import compute as ctel

        probe = create_model(args.model, max_seq_len=args.seq)
        telemetry_kwargs = dict(
            tokens_per_step=args.batch * args.seq,
            flops_per_token=ctel.lm_train_flops_per_token(
                probe.cfg, args.seq),
        )
    stop = threading.Event()
    install_preemption_handler(stop)
    with global_mesh(mesh):
        state, step, batches = build(args, mesh)
        state, history = train_loop(
            state, step, batches,
            LoopConfig(
                total_steps=args.steps,
                log_every=args.log_every,
                checkpoint_dir=args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every,
                **telemetry_kwargs,
            ),
            stop=stop,
        )
    if stop.is_set():
        print(f"preempted at step {int(state.step)}: checkpoint saved"
              if args.checkpoint_dir else
              f"preempted at step {int(state.step)} (no checkpoint dir)",
              flush=True)
    if history:
        last = history[-1]
        print(f"done: step {last['step']} "
              + " ".join(f"{k}={v:.4g}" for k, v in last.items()
                         if k != "step" and isinstance(v, float)),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
