"""Training utilities: train states, jit-able steps, optimizer factories."""

from kubeflow_tpu.train.steps import (
    TrainState,
    create_train_state,
    make_classification_train_step,
    make_lm_train_step,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_classification_train_step",
    "make_lm_train_step",
]
