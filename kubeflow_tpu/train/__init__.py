"""Training utilities: train states, jit-able steps, optimizer factories."""

from kubeflow_tpu.train.steps import (
    TrainState,
    create_train_state,
    make_classification_grad_fn,
    make_classification_train_step,
    make_grad_accum_step,
    make_lm_grad_fn,
    make_lm_train_step,
)

__all__ = [
    "TrainState",
    "create_train_state",
    "make_classification_grad_fn",
    "make_classification_train_step",
    "make_grad_accum_step",
    "make_lm_grad_fn",
    "make_lm_train_step",
    "CheckpointManager",
]


def __getattr__(name):  # lazy: orbax import is heavy
    if name == "CheckpointManager":
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        return CheckpointManager
    raise AttributeError(name)
