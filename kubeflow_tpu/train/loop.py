"""Reusable training loop: log, checkpoint, resume, eval.

The reference platform leaves every training concern to user notebooks
(SURVEY.md §2.13); this loop is the batteries the bundled images ship so a
notebook is three lines: build state, build step, ``train_loop(...)``.
Design points:

* **Resume-or-init**: pointing ``checkpoint_dir`` at an existing run
  restores the latest step into the state's shardings and continues —
  the platform's stop/start (culling) then composes with training: a
  culled-and-restarted notebook picks up where it left off.
* **Async metric fetch**: metrics are fetched (device→host) only on log
  steps, keeping the step stream free of host syncs — and the fetch is a
  scalar ``float()``, which on async/tunneled backends is the only
  reliable completion barrier (BASELINE.md measurement note).
* Pure orchestration: no jit/sharding in here — ``step_fn`` arrives
  already compiled (see parallel.train.make_sharded_train_step).
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

log = logging.getLogger("kubeflow_tpu.train")


@dataclasses.dataclass
class LoopConfig:
    total_steps: int
    log_every: int = 10
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 100
    max_to_keep: int = 3
    eval_every: int = 0          # 0 disables
    eval_steps: int = 10


def train_loop(
    state,
    step_fn: Callable,
    batches,  # Iterable, or Callable[[start_step], Iterable] for exact resume
    cfg: LoopConfig,
    *,
    eval_fn: Optional[Callable] = None,
    eval_batches: Optional[Callable[[], Iterable]] = None,
    on_log: Optional[Callable[[int, Dict[str, float]], None]] = None,
):
    """Run ``step_fn(state, batch) -> (state, metrics)`` for
    ``cfg.total_steps`` optimizer steps (counted from the restored step
    when resuming).  Returns ``(state, history)`` where history is a list
    of ``{"step": n, **metrics}`` dicts from log/eval points.
    """
    manager = None
    start_step = 0
    if cfg.checkpoint_dir:
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        manager = CheckpointManager(
            cfg.checkpoint_dir,
            max_to_keep=cfg.max_to_keep,
            save_interval_steps=cfg.checkpoint_every,
        )
        restored = manager.restore(state)
        if restored is not None:
            state = restored
            start_step = int(state.step)
            log.info("resumed from checkpoint at step %d", start_step)

    history: List[Dict[str, Any]] = []
    # A callable gets the resume point: pair it with step-indexed generators
    # (data/loader.py `start=`) and the resumed run replays the exact stream
    # an uninterrupted run would have consumed.
    it = iter(batches(start_step) if callable(batches) else batches)
    last_metrics = None
    t0 = time.perf_counter()
    window_started_at = start_step
    step = start_step

    def fetch(metrics) -> Dict[str, float]:
        return {k: float(v) for k, v in metrics.items()}

    try:
        for step in range(start_step, cfg.total_steps):
            try:
                batch = next(it)
            except StopIteration:
                log.info("data exhausted at step %d", step)
                break
            state, last_metrics = step_fn(state, batch)
            now = step + 1
            if cfg.log_every and now % cfg.log_every == 0:
                vals = fetch(last_metrics)  # completion barrier
                dt = time.perf_counter() - t0
                vals["steps_per_sec"] = (now - window_started_at) / max(dt, 1e-9)
                entry = {"step": now, **vals}
                history.append(entry)
                (on_log or _default_log)(now, vals)
                t0 = time.perf_counter()
                window_started_at = now
            if manager is not None:
                manager.save(now, state)
            if (
                cfg.eval_every
                and eval_fn is not None
                and now % cfg.eval_every == 0
            ):
                vals = _run_eval(eval_fn, state, eval_batches, cfg.eval_steps)
                entry = {"step": now, **{f"eval_{k}": v for k, v in vals.items()}}
                history.append(entry)
                (on_log or _default_log)(now, entry)
    finally:
        if manager is not None:
            final = step + 1
            if manager.latest_step() != final:
                # Final save unless the interval save already covered it.
                manager.save(final, state, force=True)
            manager.wait()
            manager.close()
    return state, history


def _run_eval(eval_fn, state, eval_batches, eval_steps) -> Dict[str, float]:
    sums: Dict[str, float] = {}
    n = 0
    source = eval_batches() if eval_batches is not None else []
    for i, batch in enumerate(source):
        if i >= eval_steps:
            break
        metrics = eval_fn(state, batch)
        for k, v in metrics.items():
            sums[k] = sums.get(k, 0.0) + float(v)
        n += 1
    return {k: v / max(n, 1) for k, v in sums.items()}


def _default_log(step: int, vals: Dict[str, float]) -> None:
    parts = " ".join(
        f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
        for k, v in vals.items() if k != "step"
    )
    print(f"step {step}: {parts}", flush=True)
