"""Reusable training loop: log, checkpoint, resume, eval — instrumented.

The reference platform leaves every training concern to user notebooks
(SURVEY.md §2.13); this loop is the batteries the bundled images ship so a
notebook is three lines: build state, build step, ``train_loop(...)``.
Design points:

* **Resume-or-init**: pointing ``checkpoint_dir`` at an existing run
  restores the latest step into the state's shardings and continues —
  the platform's stop/start (culling) then composes with training: a
  culled-and-restarted notebook picks up where it left off.
* **Async metric fetch**: metrics are fetched (device→host) only on log
  steps, keeping the step stream free of host syncs — and the fetch is a
  scalar ``float()``, which on async/tunneled backends is the only
  reliable completion barrier (BASELINE.md measurement note).
* **Step telemetry** (telemetry/compute.py): every step lands in
  ``train_step_seconds{phase=compile|run}`` and carries a span trace
  (data → dispatch → bookkeeping); log windows refresh the
  ``train_tokens_per_sec``/``train_mfu`` gauges with the SAME accounting
  bench.py prints.  A step slower than ``TRAIN_SLOW_STEP_SECONDS`` dumps
  its span tree as one JSON log line (the step-level analog of the
  control plane's slow-reconcile dumps) and, when a profile dir is
  configured, auto-captures a JAX profiler trace of the NEXT step.
* Pure orchestration: no jit/sharding in here — ``step_fn`` arrives
  already compiled (see parallel.train.make_sharded_train_step).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterable, List, Optional

from kubeflow_tpu import telemetry
from kubeflow_tpu.telemetry import compute as ctel

log = logging.getLogger("kubeflow_tpu.train")


@dataclasses.dataclass
class LoopConfig:
    total_steps: int
    log_every: int = 10
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 100
    max_to_keep: int = 3
    eval_every: int = 0          # 0 disables
    eval_steps: int = 10
    # -- telemetry accounting (all optional) ---------------------------------
    # Tokens consumed per optimizer step; inferred from a [batch, seq]
    # integer token batch when unset.  Gates the tokens/s gauge.
    tokens_per_step: Optional[int] = None
    # Model FLOPs per token (telemetry.compute.lm_train_flops_per_token —
    # the accounting bench.py documents).  Gates the MFU/TFLOPs gauges.
    flops_per_token: Optional[float] = None
    # MFU denominator; None = the v5e bf16 peak telemetry.compute pins.
    peak_tflops: Optional[float] = None
    # Auto-capture a JAX profiler trace of the step AFTER a slow one
    # (once per run).  Falls back to $KFT_SLOW_STEP_PROFILE_DIR.
    slow_step_profile_dir: Optional[str] = None


def train_loop(
    state,
    step_fn: Callable,
    batches,  # Iterable, or Callable[[start_step], Iterable] for exact resume
    cfg: LoopConfig,
    *,
    eval_fn: Optional[Callable] = None,
    eval_batches: Optional[Callable[[], Iterable]] = None,
    on_log: Optional[Callable[[int, Dict[str, float]], None]] = None,
    stop=None,
):
    """Run ``step_fn(state, batch) -> (state, metrics)`` for
    ``cfg.total_steps`` optimizer steps (counted from the restored step
    when resuming).  Returns ``(state, history)`` where history is a list
    of ``{"step": n, **metrics}`` dicts from log/eval points.

    ``stop``: optional ``threading.Event``-like object checked between
    steps — the graceful-preemption hook.  When set, the loop exits after
    the in-flight step and the ``finally`` block force-saves the current
    state (``CheckpointManager.save(..., force=True)`` + ``wait()``), so a
    SIGTERM'd pod (``train/run.py`` installs the handler) leaves a
    restorable checkpoint for the gang's next generation to resume from.
    """
    manager = None
    start_step = 0
    if cfg.checkpoint_dir:
        from kubeflow_tpu.train.checkpoint import CheckpointManager

        manager = CheckpointManager(
            cfg.checkpoint_dir,
            max_to_keep=cfg.max_to_keep,
            save_interval_steps=cfg.checkpoint_every,
        )
        restored = manager.restore(state)
        if restored is not None:
            state = restored
            start_step = int(state.step)
            log.info("resumed from checkpoint at step %d", start_step)

    history: List[Dict[str, Any]] = []
    # A callable gets the resume point: pair it with step-indexed generators
    # (data/loader.py `start=`) and the resumed run replays the exact stream
    # an uninterrupted run would have consumed.
    it = iter(batches(start_step) if callable(batches) else batches)
    last_metrics = None
    t0 = time.perf_counter()
    window_started_at = start_step
    step = start_step
    tokens_per_step = cfg.tokens_per_step
    from kubeflow_tpu.platform import config

    profile_dir = cfg.slow_step_profile_dir or config.knob(
        "KFT_SLOW_STEP_PROFILE_DIR", None,
        doc="directory for slow-step jax profiler dumps")
    profile_next = False
    profile_done = False

    def fetch(metrics) -> Dict[str, float]:
        return {k: float(v) for k, v in metrics.items()}

    try:
        for step in range(start_step, cfg.total_steps):
            if stop is not None and stop.is_set():
                log.info("stop requested at step %d; checkpointing and "
                         "exiting", step)
                break
            now = step + 1
            t_iter = time.perf_counter()
            # The run's first step pays jit compilation (for a freshly
            # built step_fn — a pre-warmed one is just a fast "compile"
            # observation); the split keeps compile stalls out of the
            # steady-state p50/p99.
            phase = "compile" if step == start_step else "run"
            ctel.train_tracer.begin(
                "train", str(now), enabled=ctel.STEP_TRACE_ENABLED)
            try:
                with ctel.train_tracer.span("data"):
                    batch = next(it)
            except StopIteration:
                ctel.train_tracer.finish("data_exhausted")
                log.info("data exhausted at step %d", step)
                break
            if tokens_per_step is None:
                tokens_per_step = _tokens_in_batch(batch)
            with ctel.train_tracer.span("dispatch", phase=phase):
                if profile_next and not profile_done:
                    profile_done, profile_next = True, False
                    with _auto_profile(profile_dir), \
                            ctel.train_tracer.span("profile",
                                                   logdir=profile_dir):
                        state, last_metrics = step_fn(state, batch)
                        _barrier(last_metrics)
                else:
                    state, last_metrics = step_fn(state, batch)
            # Step time = data + dispatch ONLY.  The bookkeeping below is
            # deliberately excluded: on async backends the log-step fetch
            # is a barrier that drains the WHOLE window's queued device
            # work — counting it would flag every log_every-th step as
            # "slow" and pollute the histogram with the logging cadence
            # (checkpoint saves and eval likewise).  Those stalls stay
            # visible as the bookkeeping span in the step trace.
            dt_step = time.perf_counter() - t_iter
            with ctel.train_tracer.span("bookkeeping"):
                if cfg.log_every and now % cfg.log_every == 0:
                    vals = fetch(last_metrics)  # completion barrier
                    dt = time.perf_counter() - t0
                    n_window = now - window_started_at
                    vals["steps_per_sec"] = n_window / max(dt, 1e-9)
                    if tokens_per_step:
                        # Same accounting as bench.py: tokens/s over the
                        # barrier-closed window; MFU = tokens/s x model
                        # FLOPs/token / chip peak (telemetry.compute).
                        vals.update(ctel.update_throughput(
                            tokens_per_step * n_window / max(dt, 1e-9),
                            flops_per_token=cfg.flops_per_token,
                            peak_tflops=cfg.peak_tflops,
                        ))
                    entry = {"step": now, **vals}
                    history.append(entry)
                    (on_log or _default_log)(now, vals)
                    t0 = time.perf_counter()
                    window_started_at = now
                if manager is not None:
                    manager.save(now, state)
                if (
                    cfg.eval_every
                    and eval_fn is not None
                    and now % cfg.eval_every == 0
                ):
                    vals = _run_eval(eval_fn, state, eval_batches,
                                     cfg.eval_steps)
                    entry = {"step": now,
                             **{f"eval_{k}": v for k, v in vals.items()}}
                    history.append(entry)
                    (on_log or _default_log)(now, entry)
            ctel.observe_step(dt_step, phase=phase)
            slow = dt_step >= ctel.TRAIN_SLOW_STEP_SECONDS
            # The dump decision rides on the data+dispatch wall, not the
            # whole trace duration (which includes bookkeeping).
            ctel.train_tracer.finish(
                "ok",
                slow_seconds=ctel.TRAIN_SLOW_STEP_SECONDS if slow else None)
            if slow:
                ctel.train_slow_steps_total.inc()
                if profile_dir and not profile_done:
                    # Capture the NEXT step: this one already ran, and a
                    # repeat of whatever stalled it is what the profile
                    # should catch.
                    profile_next = True
    finally:
        if manager is not None:
            # The state's own counter, not the loop variable: a stop-event
            # break happens at the TOP of an iteration, where step is one
            # past what the state actually contains — saving under step+1
            # would mislabel the checkpoint one step ahead.
            final = int(state.step)
            if manager.latest_step() != final:
                # Final save unless the interval save already covered it.
                manager.save(final, state, force=True)
            manager.wait()
            manager.close()
    return state, history


def _run_eval(eval_fn, state, eval_batches, eval_steps) -> Dict[str, float]:
    sums: Dict[str, float] = {}
    n = 0
    source = eval_batches() if eval_batches is not None else []
    for i, batch in enumerate(source):
        if i >= eval_steps:
            break
        metrics = eval_fn(state, batch)
        for k, v in metrics.items():
            sums[k] = sums.get(k, 0.0) + float(v)
        n += 1
    return {k: v / max(n, 1) for k, v in sums.items()}


def _default_log(step: int, vals: Dict[str, float]) -> None:
    # Structured key=value through the telemetry formatter — ONE
    # machine-parseable shape for progress lines, consistent with the
    # slow-step JSON dumps' field naming.  Printed (not just logged):
    # stdout is the notebook/pod surface operators actually watch; the
    # logger carries the same line for pipelines that configure handlers.
    line = telemetry.logfmt(
        "train_step", step=step,
        **{k: v for k, v in vals.items() if k != "step"})
    log.info("%s", line)
    print(line, flush=True)


def _tokens_in_batch(batch) -> Optional[int]:
    """Tokens an LM step consumes, inferred from the batch: a [batch, seq]
    integer array (or the first element of a (tokens, segment_ids) pair).
    None for non-token batches (images) — the tokens/s gauge then stays
    unset unless LoopConfig.tokens_per_step is given."""
    if isinstance(batch, (tuple, list)):
        if not batch:
            return None
        batch = batch[0]
    shape = getattr(batch, "shape", None)
    dtype = getattr(batch, "dtype", None)
    if shape is None or dtype is None or len(shape) != 2:
        return None
    if "int" not in str(dtype):
        return None
    return int(shape[0]) * int(shape[1])


def _barrier(metrics) -> None:
    """Force completion so a profiled step's device work lands inside the
    capture: a scalar device→host fetch when any metric converts (the
    reliable barrier on async/tunneled backends — BASELINE.md), else
    block_until_ready over whatever the step returned."""
    vals = list((metrics or {}).values())
    for v in vals:
        try:
            float(v)
            return
        except (TypeError, ValueError):
            continue
    try:
        import jax

        jax.block_until_ready(vals)
    except Exception:
        pass


@contextmanager
def _auto_profile(logdir: Optional[str]):
    """Best-effort JAX profiler capture around the slow-step follow-up:
    any profiler failure is logged and swallowed — a diagnosis aid must
    never kill (or re-run) the training step it wraps.  The interactive
    equivalent with strict semantics is train/profiling.py
    ``profile_trace``."""
    if not logdir:
        yield
        return
    import jax

    started = False
    try:
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:
        log.warning("slow-step auto-profile: start_trace failed",
                    exc_info=True)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                log.warning("slow-step auto-profile: stop_trace failed",
                            exc_info=True)
