"""The serving front door: hold-and-replay cold starts, per-tenant QoS.

Scale-to-zero used to end at an annotation contract: the controller
would wake a parked InferenceService when someone stamped ``wake-at``,
but the request that NEEDED the wake was already dropped — whoever sent
it got a connection error and the first real user of a cold service paid
with a failure.  And on the warm path, nothing stood between any one
tenant and every replica's decode-slot pool.  This module is the
component the VirtualService path was always pointing at (docs/serving.md
"The front door"):

* **Zero-drop cold starts, by construction.**  A request for a service
  with no ready endpoints is not refused — it is HELD in a bounded
  per-service queue while the activator stamps the
  ``inferenceservices.kubeflow.org/wake-at`` annotation (and re-stamps it
  while requests stay held, so a controller that read a stale stamp
  converges).  When the controller's replicas pass their real ``/readyz``
  warm generate, the held requests REPLAY into them with bounded
  full-jitter retries.  The only ways a held request fails are explicit
  and structured: hold-queue overflow (503 + Retry-After), wake deadline
  expiry (503 + Retry-After), or the request's OWN deadline expiring
  first (504 — a dead request is evicted, never replayed).
* **The QoS point.**  Admission is a per-tenant token bucket
  (``X-KFT-Tenant``; profile namespaces): past the burst, a hammering
  tenant gets structured 429 + Retry-After while other tenants' buckets
  are untouched.  Past the SLO knee — the PR-15 stored-series TTFT p99
  against the service's ``ttftP99TargetSeconds``, read from the same
  TSDB the autoscaler writes — admission applies a token SURCHARGE:
  every request costs ``KFT_ACTIVATOR_SHED_COST`` tokens instead of one,
  so the tenants driving the overload run dry (429, reason
  ``slo-shed``) while light tenants keep flowing.  Hold queues drain in
  weighted fair-share order across tenants (smooth weighted round-robin),
  and the priority class (``X-KFT-Priority``) rides through to the
  decode scheduler's admission order.
* **A data path, not a router config.**  The activator actually proxies:
  it forwards the body and the QoS/trace headers (deadline forwarded as
  the REMAINING budget, so the replica's own queue gate accounts the
  same clock), observes per-tenant TTFT into ``runtime/metrics.py``
  series the metrics pipeline self-scrapes into the TSDB, and passes
  backend responses through verbatim — including the replica's own
  structured 503-warming and 504-deadline envelopes.

Endpoint discovery is push, not probe: the InferenceService reconciler
publishes each service's ready endpoints (and its TTFT target) into the
process-shared ``EndpointBook`` every pass, and ``forget``s them on
delete — the activator never lists pods and never races the informer.

Every knob is ``KFT_ACTIVATOR_*`` through ``config.knob(validate=)``,
so the whole surface shows at ``/debug/knobs``.
"""
from __future__ import annotations

import math
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import inferenceservice as api
from kubeflow_tpu.platform.k8s.types import INFERENCESERVICE
from kubeflow_tpu.platform.runtime import metrics


# -- knobs (all surfaced at /debug/knobs) -------------------------------------

def _positive(what):
    return lambda v: None if v > 0 else f"{what} must be > 0, got {v!r}"


def _at_least(floor, what):
    return lambda v: (None if v >= floor
                      else f"{what} must be >= {floor}, got {v!r}")


def hold_queue_limit() -> int:
    return config.knob(
        "KFT_ACTIVATOR_HOLD_QUEUE", 64, int,
        doc="max requests held per service across a cold start; the "
            "next one sheds with 503 hold-overflow",
        validate=_at_least(1, "hold queue"))


def wake_deadline_seconds() -> float:
    return config.knob(
        "KFT_ACTIVATOR_WAKE_DEADLINE_SECONDS", 120.0, float,
        doc="max seconds a request stays held waiting for the wake; "
            "past it the hold sheds with 503 wake-timeout",
        validate=_positive("wake deadline"))


def restamp_seconds() -> float:
    return config.knob(
        "KFT_ACTIVATOR_RESTAMP_SECONDS", 2.0, float,
        doc="re-stamp cadence for the wake-at annotation while requests "
            "stay held (defeats a controller holding a stale stamp)",
        validate=_positive("restamp interval"))


def replay_retries() -> int:
    return config.knob(
        "KFT_ACTIVATOR_REPLAY_RETRIES", 6, int,
        doc="max full-jitter replay attempts against a just-woken "
            "service before the hold fails",
        validate=_at_least(0, "replay retries"))


def replay_base_seconds() -> float:
    return config.knob(
        "KFT_ACTIVATOR_REPLAY_BASE_SECONDS", 0.1, float,
        doc="full-jitter replay backoff base (cap doubles from here)",
        validate=_positive("replay base"))


def replay_cap_seconds() -> float:
    return config.knob(
        "KFT_ACTIVATOR_REPLAY_CAP_SECONDS", 5.0, float,
        doc="full-jitter replay backoff cap",
        validate=_positive("replay cap"))


def tenant_rate() -> float:
    return config.knob(
        "KFT_ACTIVATOR_TENANT_RATE", 50.0, float,
        doc="token-bucket refill rate per tenant, requests/second",
        validate=_positive("tenant rate"))


def tenant_burst() -> float:
    return config.knob(
        "KFT_ACTIVATOR_TENANT_BURST", 100.0, float,
        doc="token-bucket burst per tenant (bucket capacity)",
        validate=_at_least(1.0, "tenant burst"))


def tenant_weights() -> Dict[str, float]:
    """``"a=2,b=1"`` → fair-share dequeue weights; absent tenants get 1."""
    def parse(raw: str) -> Dict[str, float]:
        out = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, val = part.partition("=")
            out[name.strip()] = float(val)
        return out

    return config.knob(
        "KFT_ACTIVATOR_TENANT_WEIGHTS", {}, parse,
        doc="weighted fair-share dequeue weights, 'tenantA=2,tenantB=1' "
            "(unlisted tenants weigh 1)",
        validate=lambda v: (None if all(w > 0 for w in v.values())
                            else "weights must be > 0"))


def shed_ttft_multiple() -> float:
    return config.knob(
        "KFT_ACTIVATOR_SHED_TTFT_MULTIPLE", 4.0, float,
        doc="SLO knee: stored-series TTFT p99 above this multiple of the "
            "service's ttftP99TargetSeconds turns on admission surcharge",
        validate=_at_least(1.0, "shed multiple"))


def shed_cost() -> float:
    return config.knob(
        "KFT_ACTIVATOR_SHED_COST", 4.0, float,
        doc="tokens one request costs past the SLO knee (1 below it): "
            "the burn-driven surcharge that sheds heavy tenants first",
        validate=_at_least(1.0, "shed cost"))


# -- endpoint book ------------------------------------------------------------

class ServiceRecord:
    """What the controller knows that the data path needs: the ready
    replica base URLs and the SLO target the shed signal compares
    against."""

    __slots__ = ("endpoints", "ttft_target_s", "phase")

    def __init__(self, endpoints: Tuple[str, ...],
                 ttft_target_s: Optional[float], phase: str):
        self.endpoints = endpoints
        self.ttft_target_s = ttft_target_s
        self.phase = phase


class EndpointBook:
    """Push-model endpoint discovery: the InferenceService reconciler
    ``publish``es each pass (and ``forget``s on delete); the activator
    reads and subscribes.  Thread-safe; subscribers are called OUTSIDE
    the lock with the service key so a publish can wake held requests
    without lock-ordering games."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[str, ServiceRecord] = {}
        self._subscribers: List[Callable[[str], None]] = []

    def publish(self, key: str, *, endpoints, ttft_target_s=None,
                phase: str = "") -> None:
        rec = ServiceRecord(tuple(e for e in endpoints if e),
                            ttft_target_s, phase)
        with self._lock:
            self._records[key] = rec
            subs = list(self._subscribers)
        for fn in subs:
            fn(key)

    def forget(self, key: str) -> None:
        with self._lock:
            self._records.pop(key, None)
            subs = list(self._subscribers)
        for fn in subs:
            fn(key)

    def get(self, key: str) -> Optional[ServiceRecord]:
        with self._lock:
            return self._records.get(key)

    def subscribe(self, fn: Callable[[str], None]) -> None:
        with self._lock:
            self._subscribers.append(fn)

    def snapshot(self) -> dict:
        with self._lock:
            return {k: {"endpoints": list(r.endpoints),
                        "ttftTargetSeconds": r.ttft_target_s,
                        "phase": r.phase}
                    for k, r in self._records.items()}


_default_book: Optional[EndpointBook] = None
_default_book_lock = threading.Lock()


def default_book() -> EndpointBook:
    """The process-shared book (the ``fleetscrape.default_tsdb`` pattern):
    controllers publish into it, the activator reads from it — one
    process, one discovery truth."""
    global _default_book
    with _default_book_lock:
        if _default_book is None:
            _default_book = EndpointBook()
        return _default_book


# -- QoS primitives -----------------------------------------------------------

class TokenBucket:
    """Classic token bucket, monotonic-clock refill.  ``take(cost)``
    returns (granted, retry_after_seconds) — the retry hint is how long
    until ``cost`` tokens will have refilled, which becomes the 429's
    Retry-After."""

    def __init__(self, rate: float, burst: float, *,
                 now: Callable[[], float] = time.monotonic):
        self.rate = max(rate, 1e-9)
        self.burst = burst
        self.tokens = float(burst)
        self.now = now
        self._t = now()
        self._lock = threading.Lock()

    def take(self, cost: float = 1.0) -> Tuple[bool, float]:
        with self._lock:
            t = self.now()
            self.tokens = min(self.burst,
                              self.tokens + (t - self._t) * self.rate)
            self._t = t
            if self.tokens >= cost:
                self.tokens -= cost
                return True, 0.0
            return False, (cost - self.tokens) / self.rate


class _Waiter:
    """One held request: the worker thread parks on ``turn`` until the
    fair-share drain hands it the baton (or a deadline evicts it)."""

    __slots__ = ("tenant", "t_held")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.t_held = time.monotonic()


class _ServiceFront:
    """Per-service hold state: tenant-keyed FIFO deques drained in
    smooth weighted round-robin order.  All mutation under ``lock``;
    held threads wait on ``cond`` and re-check ``next_waiter()`` — only
    the waiter holding the baton forwards, then notifies the rest, so
    the drain ORDER is fair-share while the forwards themselves overlap."""

    def __init__(self, weights: Dict[str, float]):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.held: Dict[str, List[_Waiter]] = {}
        self.buckets: Dict[str, TokenBucket] = {}
        self.weights = weights
        self._wrr_current: Dict[str, float] = {}
        self._rr = 0
        self.last_stamp = 0.0

    def bucket(self, tenant: str) -> TokenBucket:
        with self.lock:
            b = self.buckets.get(tenant)
            if b is None:
                b = self.buckets[tenant] = TokenBucket(
                    tenant_rate(), tenant_burst())
            return b

    # All the methods below are called with ``lock`` held.

    def held_count(self) -> int:
        return sum(len(q) for q in self.held.values())

    def enqueue(self, w: _Waiter) -> None:
        self.held.setdefault(w.tenant, []).append(w)

    def remove(self, w: _Waiter) -> None:
        q = self.held.get(w.tenant)
        if q and w in q:
            q.remove(w)
        if q is not None and not q:
            del self.held[w.tenant]

    def next_waiter(self) -> Optional[_Waiter]:
        """Smooth weighted round-robin pick across tenants with held
        requests — pure read (the WRR state advances only in
        ``advance``), so every parked thread can evaluate it."""
        tenants = [t for t, q in self.held.items() if q]
        if not tenants:
            return None
        best, best_cur = None, None
        for t in sorted(tenants):
            cur = (self._wrr_current.get(t, 0.0)
                   + self.weights.get(t, 1.0))
            if best_cur is None or cur > best_cur:
                best, best_cur = t, cur
        return self.held[best][0]

    def advance(self, w: _Waiter) -> None:
        """Commit one drain: ``w`` (the current ``next_waiter``) leaves
        the queue and its tenant pays the WRR debt."""
        tenants = [t for t, q in self.held.items() if q]
        total = sum(self.weights.get(t, 1.0) for t in tenants)
        for t in tenants:
            self._wrr_current[t] = (self._wrr_current.get(t, 0.0)
                                    + self.weights.get(t, 1.0))
        self._wrr_current[w.tenant] -= total
        self.remove(w)
        if not self.held:
            self._wrr_current.clear()


# -- the activator ------------------------------------------------------------

def _default_forward(url, method, body, headers, timeout):
    """POST/GET ``url``; returns (status, headers-dict, body-bytes).
    Errors that mean 'backend unreachable' raise OSError for the replay
    loop to classify."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=body if body else None,
                                 headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


class Activator:
    """The front-door data path (module docstring has the contract).

    ``client`` writes the wake annotation; ``book`` feeds endpoint
    discovery (default: the process-shared one the controller publishes
    into); ``tsdb`` is the stored-series home of the TTFT shed signal
    (default: the process-shared store the scrape pipeline fills);
    ``forward`` is the one transport hook (hermetic tests swap it)."""

    def __init__(self, client, *, book: Optional[EndpointBook] = None,
                 tsdb=None, forward=None, timeout: float = 30.0,
                 rng: Optional[random.Random] = None,
                 now: Callable[[], float] = time.time):
        from kubeflow_tpu.telemetry import fleetscrape

        self.client = client
        self.book = book if book is not None else default_book()
        self.tsdb = tsdb if tsdb is not None else fleetscrape.default_tsdb()
        self.forward = forward or _default_forward
        self.timeout = timeout
        self.rng = rng or random.Random()
        self.now = now
        self._fronts: Dict[str, _ServiceFront] = {}
        self._fronts_lock = threading.Lock()
        self._knee_cache: Dict[str, Tuple[float, bool]] = {}
        self.book.subscribe(self._on_publish)

    # -- plumbing ---------------------------------------------------------

    def _front(self, key: str) -> _ServiceFront:
        with self._fronts_lock:
            f = self._fronts.get(key)
            if f is None:
                f = self._fronts[key] = _ServiceFront(tenant_weights())
            return f

    def _on_publish(self, key: str) -> None:
        with self._fronts_lock:
            f = self._fronts.get(key)
        if f is not None:
            with f.lock:
                f.cond.notify_all()

    def debug_snapshot(self) -> dict:
        with self._fronts_lock:
            fronts = dict(self._fronts)
        held = {}
        for key, f in fronts.items():
            with f.lock:
                if f.held:
                    held[key] = {t: len(q) for t, q in f.held.items()}
        return {"services": self.book.snapshot(), "held": held}

    # -- shed signal -------------------------------------------------------

    def _over_knee(self, key: str) -> bool:
        """Stored-series TTFT p99 past the knee?  Cached ~1s: the sample
        is a TSDB pass-join, not something to recompute per request."""
        rec = self.book.get(key)
        if rec is None or rec.ttft_target_s is None:
            return False
        cached = self._knee_cache.get(key)
        t = time.monotonic()
        if cached is not None and t - cached[0] < 1.0:
            return cached[1]
        from kubeflow_tpu.telemetry import fleetscrape

        sample = fleetscrape.serve_sample(self.tsdb, key)
        over = (sample.ttft_p99_s is not None
                and sample.ttft_p99_s
                > rec.ttft_target_s * shed_ttft_multiple())
        self._knee_cache[key] = (t, over)
        return over

    # -- wake stamping -----------------------------------------------------

    def _stamp_wake(self, ns: str, name: str, front: _ServiceFront) -> None:
        """MERGE-patch the wake annotation with the CURRENT time.  Called
        on first hold and re-called every ``restamp_seconds`` while
        requests stay held: the autoscaler wakes on a stamp postdating
        its last scale-down, so a controller replica that raced an old
        stamp converges on the next re-stamp (the staleness race pinned
        in tests/ctrlplane/test_autoscale.py)."""
        t = time.monotonic()
        with front.lock:
            if t - front.last_stamp < restamp_seconds() and front.last_stamp:
                return
            front.last_stamp = t
        try:
            self.client.patch(
                INFERENCESERVICE, name,
                {"metadata": {"annotations": {
                    api.ANNOTATION_WAKE: f"{self.now():.3f}"}}},
                ns, patch_type="merge")
            metrics.activator_wake_stamps_total.inc()
        except Exception:  # noqa: BLE001 — the hold retries on cadence
            with front.lock:
                front.last_stamp = 0.0

    # -- request path ------------------------------------------------------

    def handle(self, ns: str, name: str, rest: str, request):
        """One request through the front door; returns a werkzeug
        Response.  ``rest`` is the path past the VirtualService prefix
        (the backend sees ``/<rest>`` — the Istio rewrite, honored)."""
        from kubeflow_tpu.models.client import (
            HEADER_DEADLINE,
            HEADER_PRIORITY,
            HEADER_TENANT,
        )
        from kubeflow_tpu.platform.web.framework import failure

        key = f"{ns}/{name}"
        tenant = request.headers.get(HEADER_TENANT) or "default"
        raw_deadline = request.headers.get(HEADER_DEADLINE)
        deadline = None
        if raw_deadline:
            try:
                deadline = time.monotonic() + float(raw_deadline)
            except ValueError:
                return failure(
                    f"malformed {HEADER_DEADLINE} {raw_deadline!r}", 400)
        front = self._front(key)

        # Admission: the per-tenant token bucket, with the burn-driven
        # surcharge past the SLO knee.  This is the ONLY early-out ahead
        # of the hold path — a held request was always admitted first.
        over = self._over_knee(key)
        cost = shed_cost() if over else 1.0
        granted, wait = front.bucket(tenant).take(cost)
        if not granted:
            reason = "slo-shed" if over else "tenant-bucket"
            return self._shed(tenant, reason, 429,
                              f"tenant {tenant!r} over admission rate "
                              f"({reason})",
                              retry_after=wait)

        rec = self.book.get(key)
        if rec is None:
            metrics.activator_proxy_requests_total.labels(
                outcome="error").inc()
            return failure(f"no such service {key}", 404)
        body = request.get_data()
        headers = self._forward_headers(request, tenant, deadline,
                                        HEADER_TENANT, HEADER_PRIORITY,
                                        HEADER_DEADLINE)
        if rec.endpoints:
            return self._proxy(front, key, tenant, rest, request.method,
                               body, headers, deadline, held=False)
        return self._hold(front, ns, name, tenant, rest, request.method,
                          body, headers, deadline)

    def _forward_headers(self, request, tenant, deadline,
                         h_tenant, h_priority, h_deadline) -> dict:
        headers = {"Content-Type":
                   request.headers.get("Content-Type",
                                       "application/json"),
                   h_tenant: tenant}
        prio = request.headers.get(h_priority)
        if prio:
            headers[h_priority] = prio
        tp = request.headers.get("Traceparent") \
            or request.headers.get("traceparent")
        if tp:
            headers["traceparent"] = tp
        if deadline is not None:
            # Forwarded as the REMAINING budget (recomputed again right
            # before each attempt in _proxy): the replica's own deadline
            # gate then accounts the same clock this hold does.
            headers[h_deadline] = \
                f"{max(deadline - time.monotonic(), 0.0):.3f}"
        return headers

    def _shed(self, tenant: str, reason: str, status: int, msg: str, *,
              retry_after: Optional[float] = None):
        from kubeflow_tpu.platform.web.framework import failure

        metrics.serve_requests_shed_total.labels(
            tenant=tenant, reason=reason).inc()
        metrics.activator_proxy_requests_total.labels(outcome="shed").inc()
        headers = None
        if status in (429, 503):
            headers = {"Retry-After":
                       str(max(1, math.ceil(retry_after or 1.0)))}
        return failure(msg, status, headers=headers)

    def _hold(self, front: _ServiceFront, ns: str, name: str, tenant: str,
              rest: str, method: str, body: bytes, headers: dict,
              deadline: Optional[float]):
        """Park one request across a cold start.  The thread sleeps on
        the front's condition; a book publish (ready endpoints) or
        another drain notifies it.  Exits: fair-share turn with a ready
        endpoint (replay), own-deadline 504, wake-deadline 503, or
        overflow 503 before ever parking."""
        key = f"{ns}/{name}"
        w = _Waiter(tenant)
        with front.lock:
            if front.held_count() >= hold_queue_limit():
                # Shed OUTSIDE the queue: the bound is the promise that
                # a hold never grows past what a wake can drain.
                pass_overflow = True
            else:
                pass_overflow = False
                front.enqueue(w)
        if pass_overflow:
            return self._shed(tenant, "hold-overflow", 503,
                              f"hold queue full for {key}",
                              retry_after=wake_deadline_seconds() / 4)
        metrics.serve_requests_held.inc()
        self._stamp_wake(ns, name, front)
        give_up = time.monotonic() + wake_deadline_seconds()
        try:
            while True:
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    with front.lock:
                        front.remove(w)
                    return self._shed(tenant, "deadline", 504,
                                      "request deadline expired while "
                                      f"held for {key} to wake")
                if now >= give_up:
                    with front.lock:
                        front.remove(w)
                    return self._shed(
                        tenant, "wake-timeout", 503,
                        f"wake deadline expired holding for {key}",
                        retry_after=wake_deadline_seconds())
                self._stamp_wake(ns, name, front)
                with front.lock:
                    rec = self.book.get(key)
                    if (rec is not None and rec.endpoints
                            and front.next_waiter() is w):
                        front.advance(w)
                        break
                    waits = [give_up - now, restamp_seconds()]
                    if deadline is not None:
                        waits.append(deadline - now)
                    front.cond.wait(timeout=max(min(waits), 0.01))
            # Drained: replay outside the lock, then hand the baton on.
            with front.lock:
                front.cond.notify_all()
            return self._proxy(front, key, tenant, rest, method, body,
                               headers, deadline, held=True)
        finally:
            metrics.serve_requests_held.dec()

    def _proxy(self, front: _ServiceFront, key: str, tenant: str,
               rest: str, method: str, body: bytes, headers: dict,
               deadline: Optional[float], *, held: bool):
        """Forward with bounded full-jitter retries.  Retries cover only
        outcomes a retry can fix — transport errors and the replica's
        503 (warming / overloaded) — and stop at the request deadline;
        every other status passes through verbatim."""
        from kubeflow_tpu.models.client import full_jitter_backoff
        from kubeflow_tpu.platform.web.framework import failure
        from werkzeug.wrappers import Response

        last_err = "no ready endpoint"
        for attempt in range(replay_retries() + 1):
            if deadline is not None and time.monotonic() >= deadline:
                return self._shed(tenant, "deadline", 504,
                                  "request deadline expired during "
                                  f"replay to {key}")
            rec = self.book.get(key)
            if rec is None or not rec.endpoints:
                last_err = "no ready endpoint"
            else:
                with front.lock:
                    front._rr += 1
                    url = rec.endpoints[front._rr % len(rec.endpoints)]
                if deadline is not None:
                    headers = dict(headers)
                    headers["X-KFT-Deadline-Seconds"] = \
                        f"{max(deadline - time.monotonic(), 0.0):.3f}"
                t0 = time.perf_counter()
                try:
                    status, rhead, rbody = self.forward(
                        url + "/" + rest.lstrip("/"), method, body,
                        headers, self.timeout)
                except Exception as e:  # noqa: BLE001 — transport
                    # failure classifies as retryable
                    last_err = f"transport: {e}"
                else:
                    if status != 503:
                        metrics.serve_tenant_ttft_seconds.labels(
                            tenant=tenant).observe(
                                time.perf_counter() - t0)
                        metrics.activator_proxy_requests_total.labels(
                            outcome="replayed" if held else "ok").inc()
                        out_headers = {"Content-Type":
                                       rhead.get("Content-Type",
                                                 "application/json")}
                        if rhead.get("Retry-After"):
                            out_headers["Retry-After"] = \
                                rhead["Retry-After"]
                        return Response(rbody, status=status,
                                        headers=out_headers)
                    last_err = f"backend 503 from {url}"
            if attempt < replay_retries():
                time.sleep(full_jitter_backoff(
                    attempt, base=replay_base_seconds(),
                    cap=replay_cap_seconds(), rng=self.rng))
        metrics.activator_proxy_requests_total.labels(outcome="error").inc()
        return failure(
            f"replay budget exhausted for {key}: {last_err}", 503,
            headers={"Retry-After":
                     str(max(1, math.ceil(replay_cap_seconds())))})


_debug_registered: Optional[Activator] = None


def register_debug(activator: Optional[Activator]) -> None:
    """Single-slot debug registry (the ``jobqueue.debug_snapshot``
    pattern): ``run_controllers`` registers its live activator so the
    health port can serve ``/debug/activator`` without holding a
    reference through the WSGI closure."""
    global _debug_registered
    _debug_registered = activator


def debug_snapshot() -> Optional[dict]:
    """The registered activator's snapshot, or None when no activator
    runs in this process (the health port answers 404)."""
    act = _debug_registered
    return act.debug_snapshot() if act is not None else None


def activator_port() -> int:
    return config.knob(
        "KFT_ACTIVATOR_PORT", 8012, int,
        doc="serving front-door listen port (0 disables the activator "
            "data path in this replica)",
        validate=_at_least(0, "activator port"))


def create_activator_app(activator: Activator):
    """The WSGI front: the VirtualService path shape (``/serve/<ns>/
    <name>/<path>``) on the shared web framework, plus health and a
    debug snapshot."""
    from kubeflow_tpu.platform.web.framework import App, success

    app = App("activator")

    @app.route("/healthz")
    def healthz(request):
        return success({"healthy": True})

    @app.route("/debug/activator")
    def debug_activator(request):
        from kubeflow_tpu.platform.web.framework import json_response

        return json_response(activator.debug_snapshot())

    @app.route("/serve/<ns>/<name>/", methods=["GET", "POST"])
    def serve_root(request, ns, name):
        return activator.handle(ns, name, "", request)

    @app.route("/serve/<ns>/<name>/<path:rest>", methods=["GET", "POST"])
    def serve(request, ns, name, rest):
        return activator.handle(ns, name, rest, request)

    return app
