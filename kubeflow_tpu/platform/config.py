"""Env-driven configuration (the reference's GetEnvDefault pattern,
culling_controller.go:385-391 / notebook_controller.go:203,427,489,503)
— now a single-source **knob registry** (kftlint rule R005).

Every environment knob resolves through ``knob(name, default, parser)``:
the call both reads the environment and records the knob (name, default,
parser, doc, secrecy) in the module-level ``KNOBS`` table, so the live
surface is enumerable — ``/debug/knobs`` on the controller health port
dumps effective values (docs/analysis.md "Knob registry").  The legacy
``env/env_bool/env_int/env_float`` helpers are thin wrappers over
``knob`` and keep their exact parsing semantics.

A raw ``os.environ`` read anywhere else in the tree is a lint finding:
an undocumented knob that /debug/knobs cannot see.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, NamedTuple

_SECRET_MARKERS = (
    "TOKEN", "SECRET", "PASSWORD", "PASSWD", "CREDENTIAL", "API_KEY",
    "APIKEY", "PRIVATE",
)


class Knob(NamedTuple):
    name: str
    default: Any
    parser: Callable[[str], Any]
    doc: str
    secret: bool
    # Optional value check: returns an error message (str) for a bad
    # value, None for a good one.  A validated knob REFUSES bad env input
    # (raises ValueError) instead of silently falling back to the default
    # — for knobs where the fallback is a different code path entirely
    # (e.g. a bad KFT_SERVE_PAGE_LEN must not quietly benchmark the
    # fixed-slot pool).
    validate: Callable[[Any], Any] = None


# name -> Knob, first registration wins (a knob read from two sites with
# different defaults keeps the first-seen default in the table; each call
# still returns with ITS default — the table is documentation, not state).
KNOBS: Dict[str, Knob] = {}
_lock = threading.Lock()


def parse_bool(v: str) -> bool:
    return v.strip().lower() in ("1", "true", "yes", "on")


def knob(name: str, default: Any = None, parser: Callable[[str], Any] = str,
         *, doc: str = "", secret: bool = None,
         validate: Callable[[Any], Any] = None) -> Any:
    """Resolve env knob ``name`` through the registry: parse the env value
    when set and parseable, else ``default``.  ``secret`` defaults to a
    name sniff (TOKEN/SECRET/...) and controls /debug/knobs redaction.

    ``validate`` (value -> error-message-or-None) makes the knob strict:
    an unparseable or out-of-range env value raises ValueError instead of
    silently resolving to the default."""
    if secret is None:
        secret = any(m in name.upper() for m in _SECRET_MARKERS)
    with _lock:
        if name not in KNOBS:
            KNOBS[name] = Knob(name, default, parser, doc, secret,
                               validate)
    raw = os.environ.get(name)  # kft: disable=R005 the registry itself
    if raw is None:
        return default
    try:
        value = parser(raw)
    except (TypeError, ValueError):
        if validate is not None:
            raise ValueError(
                f"{name}={raw!r}: not a valid "
                f"{getattr(parser, '__name__', 'value')}") from None
        return default
    if validate is not None:
        problem = validate(value)
        if problem:
            raise ValueError(f"{name}={raw!r}: {problem}")
    return value


def effective(*, redact: bool = True) -> Dict[str, dict]:
    """Snapshot of every registered knob with its resolved value — the
    /debug/knobs payload.  Values re-resolve at call time (env changes
    between reads show up); secrets render as '<redacted>' when set."""
    out: Dict[str, dict] = {}
    with _lock:
        items = sorted(KNOBS.items())
    for name, k in items:
        raw = os.environ.get(name)  # kft: disable=R005 the registry itself
        if raw is None:
            value, source = k.default, "default"
        else:
            try:
                value, source = k.parser(raw), "env"
            except (TypeError, ValueError):
                # The runtime silently falls back (knob()), but the debug
                # page must not claim the environment supplied the
                # default — the typo is exactly what the reader is
                # hunting.
                value, source = k.default, "env-unparseable"
            if source == "env" and k.validate is not None:
                # Validated knobs raise at the read site; the debug page
                # reports the rejection rather than pretending the bad
                # value took effect.
                problem = k.validate(value)
                if problem:
                    value, source = k.default, "env-invalid"
        if redact and k.secret and source == "env":
            value = "<redacted>"
        if not isinstance(value, (str, int, float, bool, type(None))):
            value = str(value)
        entry = {"value": value, "default": k.default
                 if isinstance(k.default, (str, int, float, bool, type(None)))
                 else str(k.default),
                 "source": source}
        if k.doc:
            entry["doc"] = k.doc
        out[name] = entry
    return out


def env(name: str, default: str = "") -> str:
    return knob(name, default, str)


def env_bool(name: str, default: bool = False) -> bool:
    return knob(name, default, parse_bool)


def env_float(name: str, default: float) -> float:
    return knob(name, default, float)


def env_int(name: str, default: int) -> int:
    return knob(name, default, int)
