"""Env-driven configuration (the reference's GetEnvDefault pattern,
culling_controller.go:385-391 / notebook_controller.go:203,427,489,503)."""
from __future__ import annotations

import os


def env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def env_float(name: str, default: float) -> float:
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default


def env_int(name: str, default: int) -> int:
    try:
        return int(os.environ[name])
    except (KeyError, ValueError):
        return default
