"""InferenceService reconciler: CR → per-revision Deployments + Service +
VirtualService, telemetry-autoscaled (ROADMAP item 2 — the serving-side
weld).

The tensorboard controller's Deployment/Service/VirtualService shape,
grown into a real serving control loop:

* **TPU replicas** — each Deployment pod is one ``models/serve.py``
  process over ONE single-host TPU slice: ``google.com/tpu`` chip limits
  + accelerator/topology node selectors from the shared ``platform/tpu``
  math, the checkpoint reference riding as ``--checkpoint-dir`` (resolved
  by the replica through train/checkpoint.py), ``--mesh`` for per-replica
  SPMD, and a ``/readyz`` readinessProbe that runs a REAL one-token
  ``generate()`` before the pod counts as Ready.
* **Rolling weight updates** — every pod-spec-affecting field is hashed
  into a revision (apis/inferenceservice.revision_hash).  A change
  creates ``<name>-v<rev+1>`` NEXT TO the serving Deployment, warms it,
  and only after a new-revision pod is Ready AND answers the controller's
  own ``/readyz`` probe does the Service selector flip to the new
  revision label; the old Deployment is deleted after the flip — requests
  always have a ready backend (the zero-drop contract the conformance
  scenario pins).
* **Telemetry-driven autoscaling** — each reconcile scrapes the ready
  replicas' ``/metrics`` (the real serve series: ``serve_queue_depth``,
  TTFT p99 from the histogram buckets, decode-slot occupancy) and feeds
  the PURE decision function in ``runtime/autoscale.py``: target
  tracking up, cooldown-limited halving down, scale-to-zero after the
  idle window, cold-start wake on the activator annotation (or the
  traffic counter moving).  The scale state lives on the CR status, so
  any replica — and a restarted controller — continues the same decision
  sequence.
* **One quota truth** — the service's target width × slice chips is a
  declared charge in the TPUJob admission ledger
  (``runtime/jobqueue.py``); scale-ups are clamped to the profile's free
  chips (``service_headroom``), so serving can neither be promised chips
  a gang holds nor starve a gang of chips it was promised.

Runs under the same FencedClient/shards= HA regime as the other five
controllers — a scale or rollout write is fenced on the service's shard
lease.
"""
from __future__ import annotations

import time
import urllib.request
from typing import Dict, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import inferenceservice as api
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    DEPLOYMENT,
    INFERENCESERVICE,
    POD,
    SERVICE,
    VIRTUALSERVICE,
    Resource,
    deep_get,
    meta,
    name_of,
    pod_ready,
    set_owner,
    thaw,
)
from kubeflow_tpu.platform.runtime import (
    EventRecorder,
    Reconciler,
    Request,
    Result,
)
from kubeflow_tpu.platform.runtime import jobqueue as jq
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.runtime.apply import create_or_update, patch_status_diff
from kubeflow_tpu.platform.runtime.autoscale import (
    ServeSample,
    decide_scale,
    state_from_status,
    state_to_status,
    targets_from_spec,
)
from kubeflow_tpu.telemetry.metrics import quantile_from_buckets

DEFAULT_IMAGE = "ghcr.io/kubeflow-tpu/platform:latest"
# Scrape/decision cadence while replicas exist; also the requeue backstop
# for rollouts and wake watching.
DEFAULT_SYNC_S = 10.0
SCRAPE_TIMEOUT_S = 2.0


def _default_scraper(url: str) -> Optional[str]:
    """GET ``url`` with a short timeout; None on any failure (a replica
    that won't answer its scrape is simply absent from this pass)."""
    try:
        with urllib.request.urlopen(url, timeout=SCRAPE_TIMEOUT_S) as resp:
            return resp.read().decode("utf-8", "replace")
    except Exception:
        return None


def parse_serve_pages(texts: List[str]):
    """Reduce N replicas' /metrics pages in ONE parsing pass: a
    ``ServeSample`` (per-replica means for the gauges, summed counters,
    p99 over the merged TTFT buckets) plus the raw merged bucket map —
    the controller diffs the buckets between passes.  Pure
    text-in/value-out so tests and the bench drive it without a
    socket."""
    from prometheus_client.parser import text_string_to_metric_families

    n = 0
    queue_sum = 0.0
    active_sum = 0.0
    slots_sum = 0.0
    requests = 0.0
    buckets: Dict[float, float] = {}
    for text in texts:
        if not text:
            continue
        n += 1
        for fam in text_string_to_metric_families(text):
            for s in fam.samples:
                if s.name == "serve_queue_depth":
                    queue_sum += s.value
                elif s.name == "serve_decode_slots_active":
                    active_sum += s.value
                elif s.name == "serve_decode_slots":
                    slots_sum += s.value
                elif s.name == "generate_requests_total":
                    requests += s.value
                elif s.name == "serve_time_to_first_token_seconds_bucket":
                    le = float(s.labels["le"])
                    buckets[le] = buckets.get(le, 0.0) + s.value
    if n == 0:
        return ServeSample(), buckets
    occupancy = (active_sum / slots_sum) if slots_sum > 0 else None
    return ServeSample(
        replicas_scraped=n,
        queue_depth=queue_sum / n,
        ttft_p99_s=quantile_from_buckets(buckets, 0.99),
        slot_occupancy=occupancy,
        requests_total=requests,
    ), buckets


def parse_serve_sample(texts: List[str]) -> ServeSample:
    return parse_serve_pages(texts)[0]


class InferenceServiceReconciler(Reconciler):
    def __init__(self, client, *, image: Optional[str] = None,
                 cluster_domain: Optional[str] = None,
                 istio_gateway: Optional[str] = None,
                 informers: Optional[dict] = None,
                 queue: Optional[jq.JobQueue] = None,
                 scraper=None, sync_period: Optional[float] = None,
                 tsdb=None, now=time.time, book=None):
        self.client = client
        self.informers: dict = informers or {}
        self.recorder = EventRecorder(client, "inferenceservice-controller")
        self.image = image or config.env("INFERENCESERVICE_IMAGE",
                                         DEFAULT_IMAGE)
        self.cluster_domain = cluster_domain or config.env(
            "CLUSTER_DOMAIN", "cluster.local")
        self.istio_gateway = istio_gateway or config.env(
            "ISTIO_GATEWAY", "kubeflow/kubeflow-gateway")
        # The chip ledger (shared quota truth with TPUJob admission).
        # make_controller passes an informer-fed instance; bare
        # construction gets a client-backed one rebuilt per decision.
        self.queue = queue if queue is not None else jq.JobQueue(client)
        # scraper(url) -> page text or None: the ONE hook both /metrics
        # scraping and the /readyz flip probe go through, so hermetic
        # harnesses (and the bench) swap a single function.
        self.scraper = scraper or _default_scraper
        self.sync_period = (
            sync_period if sync_period is not None
            else config.env_float("INFERENCESERVICE_SYNC_SECONDS",
                                  DEFAULT_SYNC_S))
        self.now = now
        # The fleet metrics substrate (telemetry/{tsdb,fleetscrape}.py):
        # replica scrapes land in an in-process TSDB and the decision
        # sample is computed from stored series.  The old private
        # ``_ttft_prev`` bucket-delta memory is subsumed by the
        # pass-join in ``fleetscrape.serve_sample`` (A/B-pinned
        # identical in test_autoscale.py).  Bare construction gets a
        # PRIVATE store — scrape memory is per-reconciler, exactly like
        # the dict it replaced, so test instances never couple through
        # process state; ``make_controller`` passes the process-shared
        # ``default_tsdb()`` so the manager's SLO rule engine evaluates
        # the SAME series (one scrape path).  In-process either way:
        # after a restart the first pass re-baselines and reports no
        # TTFT signal.
        from kubeflow_tpu.telemetry import fleetscrape
        from kubeflow_tpu.telemetry.tsdb import TSDB

        # Endpoint discovery for the serving front door
        # (platform/activator.py): each reconcile PUBLISHES the ready
        # serving-revision endpoints (and the TTFT SLO target) into the
        # book the activator reads — push, not probe, so the data path
        # never lists pods and never races the informer.  Same
        # private/shared split as ``tsdb``: bare construction gets a
        # PRIVATE book (test instances never couple through process
        # state); ``make_controller`` passes the process-shared
        # ``activator.default_book()`` the front door reads.
        from kubeflow_tpu.platform import activator as _activator

        self.book = book if book is not None else _activator.EndpointBook()
        self.tsdb = tsdb if tsdb is not None else TSDB()
        self.fleet = fleetscrape.FleetScraper(
            self.tsdb, scraper=scraper,
            on_error=lambda reason:
                metrics.inferenceservice_scrape_errors_total.labels(
                    reason=reason).inc(),
            now=now)

    # -- cache-backed reads ---------------------------------------------------

    def _cached_get(self, gvk, name: str, ns: str) -> Optional[Resource]:
        from kubeflow_tpu.platform.runtime.informer import cache_or_client_get

        return cache_or_client_get(self.informers.get(gvk), self.client,
                                   gvk, name, ns)

    def _pods_of(self, ns: str, name: str) -> List[Resource]:
        inf = self.informers.get(POD)
        if inf is not None:
            return inf.index_list("inferenceservice", f"{ns}/{name}")
        return self.client.list(
            POD, ns, label_selector={api.LABEL_SERVICE_NAME: name})

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            svc = self.client.get(INFERENCESERVICE, req.name, req.namespace)
        except errors.NotFound:
            # ownerReference GC tears the Deployments/Service down with
            # the CR; drop the ledger charge, the scrape memory, and the
            # front door's endpoint record now.
            self.queue.forget_service(req.namespace, req.name)
            self.tsdb.drop(matcher={"service": f"{req.namespace}/{req.name}"})
            self.book.forget(f"{req.namespace}/{req.name}")
            return None

        try:
            api.validate(svc)
        except api.ValidationError as e:
            # MERGE into the stored status: wiping it would zero the
            # revision/replica record, and a later revert would then
            # cold-restart the service at revision 1 while the real
            # revision's Deployment kept its chips unowned.
            status = dict(thaw(svc.get("status")) or {})
            status["reason"] = "InvalidSpec"
            status["conditions"] = [{
                "type": "Degraded", "status": "True",
                "reason": "InvalidSpec", "message": str(e),
            }]
            if svc.get("status") != status:
                self.recorder.event(svc, "Warning",
                                    "InvalidInferenceService", str(e))
                patch_status_diff(self.client, INFERENCESERVICE, svc, status)
            return None

        ns, name = meta(svc)["namespace"], name_of(svc)
        self.queue.ensure_fresh()
        self.queue.observe_service(svc)
        slice_spec = api.tpu_slice(svc)
        now = self.now()

        # -- revision resolution ---------------------------------------------
        want_hash = api.revision_hash(svc)
        serving_rev = api.revision_of(svc)
        target_rev = api.target_revision_of(svc)
        stored_hash = deep_get(svc, "status", "revisionHash")
        # Transition counters are incremented only AFTER the status
        # commit lands (below): a faulted write replays the whole
        # reconcile, and an eager inc would count one transition N times
        # under a storm.
        deferred_incs = []
        if serving_rev == 0:
            # First reconcile: revision 1 IS the target (no rollout).
            serving_rev = target_rev = 1
        elif want_hash != stored_hash and target_rev == serving_rev:
            target_rev = serving_rev + 1
            deferred_incs.append(
                metrics.inferenceservice_rollouts_total.inc)
            self.recorder.event(
                svc, "Normal", "RolloutStarted",
                f"spec change rolls revision {serving_rev} -> {target_rev}")
        elif want_hash == stored_hash and target_rev != serving_rev:
            # Revert mid-rollout: the spec hashed back to the serving
            # revision — abandon the in-flight one (its Deployment is
            # swept below); the serving revision never stopped serving.
            target_rev = serving_rev
            self.recorder.event(
                svc, "Normal", "RolloutAbandoned",
                f"spec reverted; revision {serving_rev} keeps serving")
            self._delete_stale_deployments(ns, name, serving_rev)
        rolling = target_rev != serving_rev

        # -- pods, by revision ------------------------------------------------
        pods = self._pods_of(ns, name)
        serving_pods = self._revision_pods(pods, serving_rev)
        serving_ready = [p for p in serving_pods if pod_ready(p)]

        # -- autoscale ---------------------------------------------------------
        current = api.target_replicas_of(svc)
        if current is None:
            current = api.initial_replicas(svc)
        sample = self._scrape(svc, serving_ready)
        state = state_from_status(svc.get("status"))
        decision = decide_scale(
            current, sample, targets_from_spec(svc), state, now,
            wake_requested_at=api.wake_requested_at(svc))
        desired, reason = decision.replicas, decision.reason
        if desired > current:
            # Quota clamp: never target replicas the profile cannot pay
            # for.  ``headroom`` counts the service's own current charge
            # as free to itself, so it IS the total chips this service
            # may hold — total affordable width, not an increment.
            headroom = self.queue.service_headroom(
                ns, own_chips=current * slice_spec.chips)
            affordable = (desired if headroom == float("inf") else
                          int(max(headroom, 0.0)
                              // max(slice_spec.chips, 1)))
            if affordable < desired:
                clamped = min(desired, max(affordable, current))
                self.recorder.event(
                    svc, "Warning", "QuotaClamped",
                    f"wanted {desired} replica(s) but namespace {ns} has "
                    f"{headroom:g} free google.com/tpu chips; targeting "
                    f"{clamped}")
                desired = clamped
                reason = api.REASON_QUOTA_CLAMPED
        if desired != current:
            direction = ("up" if desired > current else
                         "to_zero" if desired == 0 else "down")
            deferred_incs.append(
                metrics.inferenceservice_scale_events_total.labels(
                    direction=direction).inc)
            if decision.reason == "Wake":
                deferred_incs.append(
                    metrics.inferenceservice_cold_starts_total.inc)
            self.recorder.event(
                svc, "Normal", "Scaled",
                f"{decision.reason or 'Scale'}: {current} -> {desired} "
                f"replica(s) (queue {sample.queue_depth:.1f}, "
                f"ttft_p99 {sample.ttft_p99_s if sample.ttft_p99_s is not None else '-'}, "
                f"occupancy {sample.slot_occupancy if sample.slot_occupancy is not None else '-'})")

        # -- reconcile children ------------------------------------------------
        flipped = False
        if rolling and desired == 0 and not serving_pods:
            # Rollout while Idle: nothing serves traffic, so the revision
            # flips by bookkeeping alone — the new weights warm on the
            # next wake, gated by the same readiness generate().
            flipped = True
            serving_rev = target_rev
        elif rolling:
            # The serving Deployment holds traffic at its current width;
            # the target revision warms NEXT TO it.  The serving
            # revision's POD TEMPLATE is never regenerated here — the
            # live spec already describes the NEW revision, and writing
            # it into the old Deployment would roll the serving pods
            # onto the new weights before readiness proved them (the
            # exact failure the revision gate exists to prevent).  Only
            # its width may change.
            self._resize_deployment(
                ns, self.deployment_name(name, serving_rev), desired)
            create_or_update(self.client, DEPLOYMENT,
                             self.generate_deployment(svc, target_rev,
                                                      max(desired, 1)))
            target_ready = [p for p in
                            self._revision_pods(pods, target_rev)
                            if pod_ready(p)]
            if target_ready and self._probe_ready(svc, target_ready[0]):
                flipped = True
                serving_rev = target_rev
                self.recorder.event(
                    svc, "Normal", "RolloutComplete",
                    f"revision {target_rev} passed its readiness "
                    "generate(); traffic flipped, old revision draining")
        else:
            create_or_update(self.client, DEPLOYMENT,
                             self.generate_deployment(svc, serving_rev,
                                                      desired))

        create_or_update(self.client, SERVICE,
                         self.generate_service(svc, serving_rev))
        create_or_update(self.client, VIRTUALSERVICE,
                         self.generate_virtual_service(svc))
        if flipped:
            # Old revisions drain only AFTER the Service flip landed.
            self._delete_stale_deployments(ns, name, serving_rev)

        # -- status ------------------------------------------------------------
        serving_pods = self._revision_pods(self._pods_of(ns, name),
                                           serving_rev)
        ready = sum(1 for p in serving_pods if pod_ready(p))
        if rolling and not flipped:
            phase = api.PHASE_ROLLING
        elif desired == 0:
            phase = api.PHASE_IDLE
        elif decision.reason == "Wake" or (current == 0 and desired > 0):
            phase = api.PHASE_WAKING
        elif ready >= desired:
            phase = api.PHASE_READY
        else:
            phase = api.PHASE_PENDING
        # Publish endpoint discovery for the activator: the READY
        # serving-revision replicas (post-flip, so a rollout's traffic
        # switch and the front door's view move together).  An empty
        # endpoint list is a real publication — it tells the front door
        # "cold: hold and wake", where a missing record means "no such
        # service: 404".
        self.book.publish(
            f"{ns}/{name}",
            endpoints=[self._endpoint_of(p, api.port_of(svc))
                       for p in serving_pods if pod_ready(p)],
            ttft_target_s=targets_from_spec(svc).ttft_p99_s,
            phase=phase)
        status = {
            "phase": phase,
            "replicas": desired,
            "readyReplicas": ready,
            "revision": serving_rev,
            "targetRevision": target_rev,
            "revisionHash": (want_hash if not rolling or flipped
                             else stored_hash),
            "reason": reason,
            # The scale subresource's labelSelectorPath.
            "selector": f"{api.LABEL_SERVICE_NAME}={name}",
            "conditions": [{
                "type": "Ready",
                "status": "True" if phase == api.PHASE_READY else "False",
                "reason": phase,
                "message": f"{ready}/{desired} replica(s) ready at "
                           f"revision {serving_rev}",
            }],
            **state_to_status(decision.state),
        }
        if svc.get("status") != status:
            patch_status_diff(self.client, INFERENCESERVICE, svc, status)
            for inc in deferred_incs:
                inc()
            try:
                self.queue.observe_service(
                    self.client.get(INFERENCESERVICE, name, ns))
            except errors.ApiError:
                pass
        # Always requeue: the autoscaler is a sampled loop, and rollouts/
        # wakes watch pod readiness.  Idle-at-zero still polls (cheap: no
        # pods to scrape) so the wake annotation is honored within one
        # period even if its watch delta is lost.
        return Result(requeue_after=self.sync_period)

    # -- scraping -------------------------------------------------------------

    @staticmethod
    def _revision_pods(pods: List[Resource], revision: int
                       ) -> List[Resource]:
        return [p for p in pods
                if deep_get(p, "metadata", "labels", api.LABEL_REVISION)
                == str(revision)]

    def _endpoint_of(self, pod: Resource, port: int) -> Optional[str]:
        override = deep_get(pod, "metadata", "annotations",
                            api.ANNOTATION_ENDPOINT)
        if override:
            return override.rstrip("/")
        ip = deep_get(pod, "status", "podIP")
        return f"http://{ip}:{port}" if ip else None

    def _scrape(self, svc: Resource,
                ready_pods: List[Resource]) -> ServeSample:
        """The real scrape path, on the fleet substrate: GET /metrics on
        every ready serving replica through the FleetScraper (one fetch
        hook, FlightPool fan-out, reason-classified failures), store the
        samples in the shared TSDB with service/replica labels, and
        compute the decision sample from stored series — TTFT p99 over
        the merged-bucket delta between this pass and the previous one,
        exactly the retired private-scrape semantics (the A/B pin in
        test_autoscale.py)."""
        from kubeflow_tpu.telemetry import fleetscrape

        ns, name = meta(svc)["namespace"], name_of(svc)
        key = f"{ns}/{name}"
        targets = fleetscrape.inferenceservice_targets(
            ready_pods, port=api.port_of(svc), service_key=key)
        self.fleet.scrape_service(key, targets)
        return fleetscrape.serve_sample(self.tsdb, key)

    def _probe_ready(self, svc: Resource, pod: Resource) -> bool:
        """The controller's OWN readiness generate() check before a
        traffic flip — the kubelet's probe gates the pod Ready condition,
        this gates the Service selector.  The probe round trip lands on
        the service's causal journey as a ``readiness_warm`` segment
        (the warm generate is where rollout-flip latency hides)."""
        from kubeflow_tpu.telemetry import causal

        url = self._endpoint_of(pod, api.port_of(svc))
        if url is None:
            return False
        t0 = time.time()
        ok = self.scraper(url + "/readyz") is not None
        ctx = causal.current()
        if ctx is not None:
            causal.record(
                "readiness_warm", trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id, segment="readiness_warm",
                start_ts=t0, end_ts=time.time(),
                object=name_of(pod), ok=ok)
        return ok

    # -- generation -----------------------------------------------------------

    @staticmethod
    def deployment_name(name: str, revision: int) -> str:
        return f"{name}-v{revision}"

    def generate_deployment(self, svc: Resource, revision: int,
                            replicas: int) -> Resource:
        ns, name = meta(svc)["namespace"], name_of(svc)
        spec = api.tpu_slice(svc)
        port = api.port_of(svc)
        image = deep_get(svc, "spec", "image") or self.image
        command = ["python", "-m", "kubeflow_tpu.models.serve",
                   "--model", api.model_of(svc), "--port", str(port)]
        ckpt = api.checkpoint_dir_of(svc)
        if ckpt:
            command += ["--checkpoint-dir", ckpt]
        if deep_get(svc, "spec", "quantize"):
            command += ["--quantize", deep_get(svc, "spec", "quantize")]
        if deep_get(svc, "spec", "mesh"):
            command += ["--mesh", deep_get(svc, "spec", "mesh")]
        if deep_get(svc, "spec", "maxSeqLen"):
            command += ["--max-seq-len",
                        str(deep_get(svc, "spec", "maxSeqLen"))]
        labels = {
            api.LABEL_SERVICE_NAME: name,
            api.LABEL_REVISION: str(revision),
        }
        container = {
            "name": "server",
            "image": image,
            "command": command,
            "ports": [{"containerPort": port}],
            "env": [
                # /metrics exposes serve_replica_revision from this, so
                # the rollout tests (and dashboards) can see which
                # weights a replica actually serves.
                {"name": "KFT_SERVE_REVISION", "value": str(revision)},
            ],
            "resources": {
                "limits": dict(spec.pod_resources()),
                "requests": dict(spec.pod_resources()),
            },
            # Ready means "generated a token": the probe runs (and
            # caches) a one-token warm generate(), so a flip never
            # routes traffic to a replica that would compile-stall or
            # crash on its first request.
            "readinessProbe": {
                "httpGet": {"path": "/readyz", "port": port},
                "periodSeconds": 5,
                "failureThreshold": 3,
            },
        }
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": self.deployment_name(name, revision),
                "namespace": ns,
                "labels": dict(labels),
            },
            "spec": {
                "replicas": replicas,
                "selector": {"matchLabels": dict(labels)},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": {
                        "containers": [container],
                        "nodeSelector": dict(spec.node_selectors()),
                    },
                },
            },
        }
        set_owner(deployment, svc)
        return deployment

    def generate_service(self, svc: Resource, revision: int) -> Resource:
        ns, name = meta(svc)["namespace"], name_of(svc)
        out = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {api.LABEL_SERVICE_NAME: name}},
            "spec": {
                # BOTH labels: the revision selector is the rollout's
                # atomic traffic switch.
                "selector": {
                    api.LABEL_SERVICE_NAME: name,
                    api.LABEL_REVISION: str(revision),
                },
                "ports": [{"name": "http-serve", "port": 80,
                           "targetPort": api.port_of(svc)}],
            },
        }
        set_owner(out, svc)
        return out

    def generate_virtual_service(self, svc: Resource) -> Resource:
        ns, name = meta(svc)["namespace"], name_of(svc)
        vs = {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"inferenceservice-{ns}-{name}",
                         "namespace": ns},
            "spec": {
                "hosts": ["*"],
                "gateways": [self.istio_gateway],
                "http": [{
                    "match": [{"uri": {
                        "prefix": f"/serve/{ns}/{name}/"}}],
                    "rewrite": {"uri": "/"},
                    "route": [{"destination": {
                        "host": f"{name}.{ns}.svc.{self.cluster_domain}",
                        "port": {"number": 80},
                    }}],
                }],
            },
        }
        set_owner(vs, svc)
        return vs

    def _resize_deployment(self, ns: str, dep_name: str,
                           replicas: int) -> None:
        """Width-only update of a live Deployment (the mid-rollout
        serving revision): its stored pod template — the spec snapshot
        its revision was generated from — is left untouched."""
        cur = self._cached_get(DEPLOYMENT, dep_name, ns)
        if cur is None or deep_get(cur, "spec", "replicas") == replicas:
            return
        live = thaw(cur)
        live["spec"]["replicas"] = replicas
        create_or_update(self.client, DEPLOYMENT, live)

    def _delete_stale_deployments(self, ns: str, name: str,
                                  keep_revision: int) -> None:
        inf = self.informers.get(DEPLOYMENT)
        if inf is not None:
            deployments = inf.index_list("inferenceservice", f"{ns}/{name}")
        else:
            deployments = self.client.list(
                DEPLOYMENT, ns,
                label_selector={api.LABEL_SERVICE_NAME: name})
        for d in deployments:
            rev = deep_get(d, "metadata", "labels", api.LABEL_REVISION)
            if rev == str(keep_revision):
                continue
            try:
                self.client.delete(DEPLOYMENT, name_of(d), ns)
            except errors.NotFound:
                pass


# -- watch mappers / indexers -------------------------------------------------


def pods_to_service_requests(obj: Resource) -> List[Request]:
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    svc = labels.get(api.LABEL_SERVICE_NAME)
    if not svc:
        return []
    return [Request(deep_get(obj, "metadata", "namespace", default=""), svc)]


def _service_label_index(obj: Resource) -> List[str]:
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    svc = labels.get(api.LABEL_SERVICE_NAME)
    ns = deep_get(obj, "metadata", "namespace", default="")
    return [f"{ns}/{svc}"] if svc else []


def make_controller(client, **kwargs):
    from kubeflow_tpu.platform.k8s.types import NODE, RESOURCEQUOTA
    from kubeflow_tpu.platform.runtime import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer

    shards = kwargs.pop("shards", None)
    informers = {
        INFERENCESERVICE: Informer(client, INFERENCESERVICE),
        DEPLOYMENT: Informer(
            client, DEPLOYMENT,
            indexers={"inferenceservice": _service_label_index}),
        POD: Informer(client, POD,
                      indexers={"inferenceservice": _service_label_index}),
        SERVICE: Informer(client, SERVICE),
    }
    # The ledger feed is UNSHARDED for the same reason the tpujob
    # controller's is: every replica must compute the same quota truth
    # for the keys it owns.  (Each controller keeps its own ledger
    # instance; both are pure functions of the same watch state.)
    queue = jq.JobQueue()
    queue.informer_backed = True
    queue_informers = {
        INFERENCESERVICE: Informer(client, INFERENCESERVICE),
        RESOURCEQUOTA: Informer(client, RESOURCEQUOTA),
        NODE: Informer(client, NODE),
    }
    from kubeflow_tpu.platform.k8s.types import TPUJOB

    queue_informers[TPUJOB] = Informer(client, TPUJOB)

    def _on_service_delta(etype, obj):
        ns = deep_get(obj, "metadata", "namespace", default="") or ""
        if etype == "DELETED":
            queue.forget_service(ns, name_of(obj))
        else:
            queue.observe_service(obj)

    def _on_job_delta(etype, obj):
        ns = deep_get(obj, "metadata", "namespace", default="") or ""
        if etype == "DELETED":
            queue.forget(ns, name_of(obj))
        else:
            queue.observe(obj)

    queue_informers[INFERENCESERVICE].add_handler(_on_service_delta)
    queue_informers[TPUJOB].add_handler(_on_job_delta)
    queue_informers[RESOURCEQUOTA].add_handler(
        lambda _e, _o: queue.set_quotas(
            queue_informers[RESOURCEQUOTA].list()))
    queue_informers[NODE].add_handler(
        lambda _e, _o: queue.set_nodes(queue_informers[NODE].list()))

    # Production wiring scrapes into the process-shared store so the
    # manager's SLO rule engine reads the same serve series (ONE scrape
    # path — docs/observability.md "The metrics pipeline"); explicit
    # tsdb= overrides for hermetic harnesses.
    from kubeflow_tpu.telemetry import fleetscrape

    from kubeflow_tpu.platform import activator as _activator

    kwargs.setdefault("tsdb", fleetscrape.default_tsdb())
    kwargs.setdefault("book", _activator.default_book())
    reconciler = InferenceServiceReconciler(client, informers=informers,
                                            queue=queue, **kwargs)

    def on_start():
        metrics.register_inferenceservice_collector(client)
        for informer in queue_informers.values():
            informer.start()
        for informer in queue_informers.values():
            # Best-effort: an unsynced ledger degrades to permissive
            # headroom until the feed lands — never a startup failure.
            informer.wait_for_sync(30.0)

    def on_stop():
        metrics.register_inferenceservice_collector(None)
        for informer in queue_informers.values():
            informer.stop()

    return Controller(
        "inferenceservice-controller",
        reconciler,
        primary=INFERENCESERVICE,
        owns=[DEPLOYMENT, SERVICE, VIRTUALSERVICE],
        watches=[(POD, pods_to_service_requests)],
        informers=informers,
        on_start=on_start,
        on_stop=on_stop,
        resync_period=300.0,
        shards=shards,
    )
