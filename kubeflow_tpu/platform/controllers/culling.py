"""Culling controller: stop idle notebooks to release TPU chips.

Same contract as the reference culler (reference culling_controller.go:
78-162 loop, 202-241 kernel probe, 243-255 idleness check, 179-200 window):
probe the Jupyter kernels API over cluster DNS, and when every kernel has
been idle past CULL_IDLE_TIME, set the ``kubeflow-resource-stopped``
annotation — the notebook reconciler then scales the whole slice to zero.
Culling matters *more* on TPU: an idle v5e-4x8 notebook is 32 parked chips.

Multi-host nuance (SURVEY.md §7 hard part b): the kernel API only exists on
worker 0, and the per-notebook Service already routes there, so the probe
URL is identical for single- and multi-host slices.

The HTTP prober is injected (tests use a fake; production uses requests).
"""
from __future__ import annotations

import datetime
import json
import logging
import threading
import time
from typing import Callable, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import notebook as nbapi
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    NOTEBOOK,
    Resource,
    deep_get,
    meta,
    name_of,
)
from kubeflow_tpu.platform.runtime import Reconciler, Request, Result
from kubeflow_tpu.platform.runtime import metrics

TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"

# Kernel execution states that count as "busy" (probe returns Jupyter's
# /api/kernels JSON; anything not idle keeps the notebook alive).
IDLE_STATE = "idle"

Prober = Callable[[str], Optional[List[dict]]]  # url -> kernels or None on error


def default_prober(url: str, *, timeout: Optional[float] = None
                   ) -> Optional[List[dict]]:
    """HTTP probe of the Jupyter kernels API.  ``timeout`` is the whole
    per-probe budget (env ``CULL_PROBE_TIMEOUT_SECONDS``) — a wedged user
    pod must cost a bounded slice of the worker's cycle, never a hang."""
    import requests

    if timeout is None:
        timeout = config.env_float("CULL_PROBE_TIMEOUT_SECONDS", 10.0)
    try:
        resp = requests.get(url, timeout=timeout)
        if resp.status_code != 200:
            return None
        data = resp.json()
        return data if isinstance(data, list) else None
    except (requests.RequestException, json.JSONDecodeError):
        return None


class CullingReconciler(Reconciler):
    def __init__(
        self,
        client,
        *,
        prober: Optional[Prober] = None,
        idle_minutes: Optional[float] = None,
        check_period_minutes: Optional[float] = None,
        cluster_domain: Optional[str] = None,
        now: Optional[Callable[[], datetime.datetime]] = None,
        cache=None,
        probe_timeout: Optional[float] = None,
        probe_budget_s: Optional[float] = None,
    ):
        self.client = client
        # Optional Notebook Informer (make_controller wires the same one
        # the controller watches through): reconcile then reads the
        # notebook from the shared cache as a zero-copy frozen view
        # instead of one apiserver GET per probe period per notebook.
        self.cache = cache
        self.probe_timeout = (
            probe_timeout if probe_timeout is not None
            else config.env_float("CULL_PROBE_TIMEOUT_SECONDS", 10.0)
        )
        # Per-cycle probe budget: cumulative wall seconds the reconcilers
        # may spend probing per check period (all workers combined).  Once
        # exhausted, remaining notebooks this cycle count as BUSY and are
        # re-checked next period — a fleet of wedged pods degrades culling
        # to "slower", never to "the probe loop ate the controller".
        # 0 = unlimited (the default; operators opt in).
        self.probe_budget_s = (
            probe_budget_s if probe_budget_s is not None
            else config.env_float("CULL_PROBE_BUDGET_SECONDS", 0.0)
        )
        self._budget_lock = threading.Lock()
        self._budget_window_start: Optional[float] = None
        self._budget_used = 0.0
        if prober is not None:
            self.prober = prober
        else:
            self.prober = lambda url: default_prober(
                url, timeout=self.probe_timeout)
        self.idle_minutes = (
            idle_minutes
            if idle_minutes is not None
            else config.env_float("CULL_IDLE_TIME", 1440.0)
        )
        self.check_period = (
            check_period_minutes
            if check_period_minutes is not None
            else config.env_float("IDLENESS_CHECK_PERIOD", 1.0)
        )
        self.cluster_domain = cluster_domain or config.env("CLUSTER_DOMAIN", "cluster.local")
        self.dev = config.env_bool("DEV", False)
        self._now = now or (lambda: datetime.datetime.now(datetime.timezone.utc))
        # (ns, name) -> wall-clock datetime (self._now()) of the last
        # probe — the injectable clock, so tests drive it; a backwards
        # clock step is clamped in reconcile.  The probe schedule is
        # the CHECK PERIOD, not the event rate: every
        # reconcile of a busy notebook patches the last-activity
        # annotation, whose MODIFIED delta re-enqueues the key — without
        # this throttle that loop probes the user pod at ~probe-latency
        # rate instead of once per period (review r5).  Resyncs and
        # unrelated notebook updates are throttled identically, so an
        # operator's IDLENESS_CHECK_PERIOD actually governs probe load.
        self._last_probe: dict = {}

    # -- probe url -----------------------------------------------------------

    def kernels_url(self, namespace: str, name: str) -> str:
        # Through the per-notebook Service (port 80 → worker 0), under the
        # NB_PREFIX base path the server runs with.  DEV mode reaches the
        # Service through a local kubectl proxy instead of cluster DNS
        # (reference culling_controller.go:211-216).
        prefix = nbapi.nb_prefix(namespace, name)
        if self.dev:
            port_name = nbapi.service_port_name(name)
            return (
                f"http://localhost:8001/api/v1/namespaces/{namespace}"
                f"/services/{name}:{port_name}/proxy{prefix}/api/kernels"
            )
        return (
            f"http://{name}.{namespace}.svc.{self.cluster_domain}"
            f"{prefix}/api/kernels"
        )

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        now = self._now()
        key = (req.namespace, req.name)
        last_probe_at = self._last_probe.get(key)
        period_s = self.check_period * 60.0
        if last_probe_at is not None:
            since = (now - last_probe_at).total_seconds()
            # The throttle runs BEFORE any apiserver read, or an event
            # storm still costs one GET per delta.  Negative `since` (a
            # wall-clock step backwards — these are _now() datetimes, not
            # monotonic) falls through and probes rather than extending
            # the suppression by the skew.
            if 0 <= since < period_s:
                # Probed recently: don't let watch events / resyncs turn
                # the check period into the event rate.  (A just-deleted/
                # stopped notebook's throttle entry lingers at most one
                # period before the cleanup below sees it.)
                return Result(requeue_after=period_s - since)

        requeue = Result(requeue_after=period_s)
        notebook = self._get_notebook(req.name, req.namespace)
        if notebook is None:
            self._last_probe.pop(key, None)
            return None
        if nbapi.is_stopped(notebook):
            self._last_probe.pop(key, None)
            return None  # nothing to cull; notebook reconciler handles restart

        self._last_probe[key] = now

        kernels = self._safe_probe(req.namespace, req.name)
        if kernels is None:
            # Unreachable / errored / over budget (starting, crashing,
            # mid-scale, broken prober) — FAIL SAFE: a notebook whose
            # idleness probe can't answer counts as BUSY and is never
            # culled blind.  Next period retries.
            return requeue
        if not self._all_idle(kernels):
            self._record_activity(notebook, now)
            return requeue

        last = self._last_activity(notebook, kernels)
        if last is None:
            self._record_activity(notebook, now)
            return requeue
        idle_for = (now - last).total_seconds() / 60.0
        if idle_for < self.idle_minutes:
            return requeue

        # One-annotation merge patch: the cull write touches exactly the
        # stop marker — no thaw of the frozen cache view, no full-object
        # PUT, and no resourceVersion to 409 against the notebook
        # controller's concurrent status writes.
        self.client.patch(
            NOTEBOOK, req.name,
            {"metadata": {"annotations": {
                nbapi.STOP_ANNOTATION: now.strftime(TIME_FORMAT)}}},
            req.namespace,
        )
        metrics.notebook_culling_total.inc()
        metrics.last_culling_timestamp.set(now.timestamp())
        return None

    def _safe_probe(self, namespace: str, name: str) -> Optional[List[dict]]:
        """Run the prober under the fail-safe contract: ANY exception (a
        raising prober must not crash-loop the reconcile into backoff —
        with a broken probe endpoint that loop would probe at retry rate
        forever) and an exhausted per-cycle budget both answer None, which
        reconcile treats as busy.  Probe wall time is charged against the
        budget window."""
        reserved = 0.0
        if self.probe_budget_s > 0:
            now_mono = time.monotonic()
            period_s = max(self.check_period * 60.0, 1e-9)
            with self._budget_lock:
                if (self._budget_window_start is None
                        or now_mono - self._budget_window_start >= period_s):
                    self._budget_window_start = now_mono
                    self._budget_used = 0.0
                if self._budget_used >= self.probe_budget_s:
                    metrics.culling_probe_failures_total.inc()
                    return None
                # RESERVE the worst case (the probe timeout) before
                # probing: with N concurrent workers, check-then-spend
                # accounting would let all N pass the gate while each
                # other's probes are still in flight — overshooting an
                # operator's budget by workers x timeout per window.  The
                # reservation is trued up to actual cost below.
                reserved = self.probe_timeout
                self._budget_used += reserved
        t0 = time.monotonic()
        try:
            kernels = self.prober(self.kernels_url(namespace, name))
        except Exception:
            logging.getLogger("kubeflow_tpu.culling").warning(
                "idleness probe for %s/%s raised; counting as busy",
                namespace, name, exc_info=True)
            kernels = None
        finally:
            if self.probe_budget_s > 0:
                with self._budget_lock:
                    self._budget_used += (time.monotonic() - t0) - reserved
        if kernels is None:
            metrics.culling_probe_failures_total.inc()
        return kernels

    def _get_notebook(self, name: str, namespace: str) -> Optional[Resource]:
        """Frozen cache read when the shared informer is wired and synced
        (the probe throttle already makes this path freshness-tolerant);
        live GET otherwise.  None when the notebook is gone."""
        from kubeflow_tpu.platform.runtime.informer import cache_or_client_get

        return cache_or_client_get(self.cache, self.client, NOTEBOOK,
                                   name, namespace)

    # -- idleness ------------------------------------------------------------

    @staticmethod
    def _all_idle(kernels: List[dict]) -> bool:
        return all(k.get("execution_state") == IDLE_STATE for k in kernels)

    def _last_activity(self, notebook: Resource, kernels: List[dict]):
        """Most recent activity across kernels; falls back to the annotation
        (kernel-less servers still get culled from their last known touch)."""
        stamps = []
        for k in kernels:
            ts = _parse_time(k.get("last_activity"))
            if ts:
                stamps.append(ts)
        ann = _parse_time(
            (deep_get(notebook, "metadata", "annotations", default={}) or {}).get(
                nbapi.LAST_ACTIVITY_ANNOTATION
            )
        )
        if ann:
            stamps.append(ann)
        return max(stamps) if stamps else None

    def _record_activity(self, notebook: Resource, now) -> None:
        annotations = deep_get(notebook, "metadata", "annotations", default={}) or {}
        stamp = now.strftime(TIME_FORMAT)
        if annotations.get(nbapi.LAST_ACTIVITY_ANNOTATION) == stamp:
            return
        self.client.patch(
            NOTEBOOK,
            name_of(notebook),
            {"metadata": {"annotations": {nbapi.LAST_ACTIVITY_ANNOTATION: stamp}}},
            deep_get(notebook, "metadata", "namespace"),
        )


def _parse_time(value: Optional[str]):
    if not value:
        return None
    for fmt in (TIME_FORMAT, "%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%S.%f%z",
                "%Y-%m-%dT%H:%M:%S%z"):
        try:
            dt = datetime.datetime.strptime(value, fmt)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=datetime.timezone.utc)
            return dt
        except ValueError:
            continue
    return None


def make_controller(client, *, notebook_informer=None, **kwargs):
    from kubeflow_tpu.platform.runtime import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer

    # The reconciler reads notebooks from the SAME cache the controller
    # watches through (owned or shared) — zero-copy frozen views instead
    # of one apiserver GET per probe (reconcile thaws only on the cull
    # write).
    shards = kwargs.pop("shards", None)
    owned = (Informer(client, NOTEBOOK)
             if notebook_informer is None else None)
    kwargs.setdefault("cache", notebook_informer
                      if notebook_informer is not None else owned)
    reconciler = CullingReconciler(client, **kwargs)
    return Controller(
        "culling-controller",
        reconciler,
        primary=NOTEBOOK,
        # Informer-sourced like the notebook controller: a raw watch
        # re-listed every notebook as ADDED on each bounded-window
        # rollover, and for THIS controller every spurious reconcile is
        # an HTTP probe into a user pod.  ``notebook_informer`` lets the
        # manager process SHARE the notebook controller's informer (one
        # LIST+WATCH stream and one cache for the kind — the
        # controller-runtime shared-cache model; Informer.start is
        # idempotent for exactly this).  The reconciler's per-key probe
        # throttle keeps the probe rate at the check period regardless
        # of delta rate.
        # Explicit None check: Informer defines __len__, so an EMPTY
        # shared informer is falsy and `or` would silently discard it.
        # A passed-in informer goes in shared_informers — this controller
        # must never stop the notebook controller's cache.
        informers=(None if notebook_informer is not None
                   else {NOTEBOOK: owned}),
        shared_informers=({NOTEBOOK: notebook_informer}
                          if notebook_informer is not None else None),
        # The resync re-seeds parked requeues after a restart; it runs at
        # the operator's check period (not a hardcoded faster one, which
        # silently overrode IDLENESS_CHECK_PERIOD > 1 min) and reads the
        # informer cache, not the apiserver.
        resync_period=max(60.0, reconciler.check_period * 60.0),
        # Probes are blocking I/O (default_prober timeout 10 s): with one
        # worker a single unreachable notebook stalls every other
        # notebook's idleness check for the whole timeout, and a fleet of
        # N notebooks needs N sequential probes per check period.  Eight
        # workers probe concurrently; the workqueue's per-key exclusion
        # keeps the single-reconciler-per-notebook guarantee.
        workers=8,
        shards=shards,
    )
