"""Profile controller: one Profile CR = one user workspace.

Mirrors the reference semantics (reference profile_controller.go:105-331):
create/adopt the namespace (rejecting takeover of foreign namespaces),
stamp RBAC (editor/viewer service accounts + role bindings, owner admin
binding), emit the Istio AuthorizationPolicy that makes the trusted
user-header model safe, and materialize the per-namespace ResourceQuota —
which on this platform is where **TPU chip quotas** live
(``google.com/tpu`` in ``spec.resourceQuotaSpec.hard``, the north-star
quota hook; reference :253-280 only ever carried CPU/memory).

Cloud-identity plugins (GCP Workload Identity / AWS IRSA,
reference plugin_workload_identity.go / plugin_iam.go) keep the same CR
contract; the cloud IAM round-trip is behind an injectable interface so the
in-cluster annotation side works everywhere and clouds plug in via config.
A ``profile-finalizer`` drives revocation on delete.
"""
from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    AUTHORIZATIONPOLICY,
    NAMESPACE,
    PROFILE,
    RESOURCEQUOTA,
    ROLEBINDING,
    SERVICEACCOUNT,
    Resource,
    deep_get,
    meta,
    name_of,
    set_owner,
)
from kubeflow_tpu.platform.runtime import EventRecorder, Reconciler, Request, Result
from kubeflow_tpu.platform.runtime import apply
from kubeflow_tpu.platform.runtime import metrics

OWNER_ANNOTATION = "owner"
FINALIZER = "profile-finalizer"
QUOTA_NAME = "kf-resource-quota"
AUTH_POLICY_NAME = "ns-owner-access-istio"

EDITOR_SA = "default-editor"
VIEWER_SA = "default-viewer"
ADMIN_BINDING = "namespaceAdmin"
CLUSTER_ROLE_ADMIN = "kubeflow-admin"
CLUSTER_ROLE_EDIT = "kubeflow-edit"
CLUSTER_ROLE_VIEW = "kubeflow-view"


class ProfilePlugin:
    """Apply/Revoke contract (reference profile_controller.go:77-83)."""

    kind = ""

    def apply(self, client, profile: Resource, plugin_spec: dict) -> None: ...

    def revoke(self, client, profile: Resource, plugin_spec: dict) -> None: ...


class WorkloadIdentityPlugin(ProfilePlugin):
    """GCP: annotate the editor KSA; IAM binding via injected callback.

    The IAM member must carry the cluster's workload-identity pool
    (``PROJECT_ID.svc.id.goog``), resolved from the constructor or the
    WORKLOAD_IDENTITY_POOL / GCP_PROJECT env (reference
    plugin_workload_identity.go builds the same member string).
    """

    kind = "WorkloadIdentity"

    def __init__(self, bind_iam: Optional[Callable[[str, str, bool], None]] = None,
                 *, identity_pool: Optional[str] = None):
        self.bind_iam = bind_iam  # (gcp_sa, member, add) -> None
        pool = identity_pool or config.env("WORKLOAD_IDENTITY_POOL")
        if not pool and config.env("GCP_PROJECT"):
            pool = f"{config.env('GCP_PROJECT')}.svc.id.goog"
        self.identity_pool = pool

    def _member(self, profile: Resource) -> str:
        return (
            f"serviceAccount:{self.identity_pool}"
            f"[{name_of(profile)}/{EDITOR_SA}]"
        )

    def _annotate(self, client, profile, gcp_sa: Optional[str]) -> None:
        ns = name_of(profile)
        sa = client.get(SERVICEACCOUNT, EDITOR_SA, ns)
        annotations = meta(sa).setdefault("annotations", {})
        if gcp_sa:
            annotations["iam.gke.io/gcp-service-account"] = gcp_sa
        else:
            annotations.pop("iam.gke.io/gcp-service-account", None)
        client.update(sa)

    def apply(self, client, profile, plugin_spec) -> None:
        gcp_sa = plugin_spec.get("gcpServiceAccount", "")
        self._annotate(client, profile, gcp_sa)
        if self.bind_iam and gcp_sa and self.identity_pool:
            self.bind_iam(gcp_sa, self._member(profile), True)

    def revoke(self, client, profile, plugin_spec) -> None:
        gcp_sa = plugin_spec.get("gcpServiceAccount", "")
        if self.bind_iam and gcp_sa and self.identity_pool:
            self.bind_iam(gcp_sa, self._member(profile), False)


class IrsaPlugin(ProfilePlugin):
    """AWS IRSA: role-arn annotation; trust-policy edit via injected callback."""

    kind = "AwsIamForServiceAccount"

    def __init__(self, edit_trust: Optional[Callable[[str, str, bool], None]] = None):
        self.edit_trust = edit_trust

    def apply(self, client, profile, plugin_spec) -> None:
        arn = plugin_spec.get("awsIamRole", "")
        ns = name_of(profile)
        sa = client.get(SERVICEACCOUNT, EDITOR_SA, ns)
        meta(sa).setdefault("annotations", {})["eks.amazonaws.com/role-arn"] = arn
        client.update(sa)
        if self.edit_trust and arn:
            self.edit_trust(arn, f"system:serviceaccount:{ns}:{EDITOR_SA}", True)

    def revoke(self, client, profile, plugin_spec) -> None:
        arn = plugin_spec.get("awsIamRole", "")
        if self.edit_trust and arn:
            ns = name_of(profile)
            self.edit_trust(arn, f"system:serviceaccount:{ns}:{EDITOR_SA}", False)


class ProfileReconciler(Reconciler):
    def __init__(
        self,
        client,
        *,
        userid_header: Optional[str] = None,
        userid_prefix: Optional[str] = None,
        default_namespace_labels: Optional[Dict[str, str]] = None,
        default_namespace_labels_path: Optional[str] = None,
        plugins: Optional[List[ProfilePlugin]] = None,
        notebook_controller_sa: str = "system:serviceaccount:kubeflow:notebook-controller-service-account",
    ):
        self.client = client
        self.recorder = EventRecorder(client, "profile-controller")
        self.userid_header = userid_header or config.env("USERID_HEADER", "kubeflow-userid")
        self.userid_prefix = (
            userid_prefix if userid_prefix is not None else config.env("USERID_PREFIX", "")
        )
        self.labels_path = default_namespace_labels_path
        self.default_labels = default_namespace_labels or {
            "istio-injection": "enabled",
            "app.kubernetes.io/part-of": "kubeflow-profile",
        }
        self.plugins = {p.kind: p for p in (plugins or [WorkloadIdentityPlugin(), IrsaPlugin()])}
        self.notebook_controller_sa = notebook_controller_sa

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            profile = self.client.get(PROFILE, req.name)
        except errors.NotFound:
            return None

        if meta(profile).get("deletionTimestamp"):
            self._revoke_plugins(profile)
            finalizers = [f for f in meta(profile).get("finalizers", []) if f != FINALIZER]
            profile = copy.deepcopy(profile)
            meta(profile)["finalizers"] = finalizers
            self.client.update(profile)
            return None

        if FINALIZER not in meta(profile).get("finalizers", []):
            profile = copy.deepcopy(profile)
            meta(profile).setdefault("finalizers", []).append(FINALIZER)
            profile = self.client.update(profile)

        if not self._counted("namespace", self._reconcile_namespace, profile):
            return None  # ownership conflict surfaced on status
        self._counted("serviceaccount", self._reconcile_service_accounts, profile)
        self._counted("rolebinding", self._reconcile_role_bindings, profile)
        self._counted("authorizationpolicy", self._reconcile_authorization_policy, profile)
        self._counted("resourcequota", self._reconcile_resource_quota, profile)
        self._counted("plugin", self._apply_plugins, profile)
        self._set_ready(profile)
        return None

    def _counted(self, kind: str, fn, *args):
        """Per-kind request/failure counters around each reconcile step
        (reference monitoring.go:28-44 IncRequestCounter pattern)."""
        try:
            result = fn(*args)
        except Exception:
            metrics.request_kf_failure.labels(
                component="profile", kind=kind, severity=metrics.SEVERITY_MAJOR
            ).inc()
            raise
        metrics.request_kf.labels(component="profile", kind=kind).inc()
        return result

    # -- namespace -----------------------------------------------------------

    def _current_default_labels(self) -> Dict[str, str]:
        """Default namespace labels, re-read from the mounted file on every
        reconcile when a path is configured — paired with the mtime watcher
        in make_controller this gives the reference's hot-reload semantics
        (reference profile_controller.go:368-399, :762-777)."""
        if self.labels_path:
            import yaml

            try:
                with open(self.labels_path) as f:
                    data = yaml.safe_load(f) or {}
            except (OSError, yaml.YAMLError):
                # A bad config edit must not wedge every Profile reconcile;
                # fall back to the static defaults until the file is fixed.
                return dict(self.default_labels)
            if isinstance(data, dict):
                return {str(k): str(v) for k, v in data.items()}
        return dict(self.default_labels)

    def _reconcile_namespace(self, profile: Resource) -> bool:
        name = name_of(profile)
        owner = deep_get(profile, "spec", "owner", "name", default="")
        default_labels = self._current_default_labels()
        try:
            ns = self.client.get(NAMESPACE, name)
        except errors.NotFound:
            ns = {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {
                    "name": name,
                    "annotations": {OWNER_ANNOTATION: owner},
                    "labels": dict(default_labels),
                },
            }
            set_owner(ns, profile)
            apply.create(self.client, ns)
            return True
        existing_owner = deep_get(ns, "metadata", "annotations", OWNER_ANNOTATION)
        if existing_owner is None:
            # Pre-existing namespace not created for a profile: refuse to
            # take it over (reference :127-198 ownership check).
            self._set_failed(
                profile,
                f"namespace {name} exists and is not owned by any profile",
            )
            return False
        if existing_owner != owner:
            self._set_failed(
                profile,
                f"namespace {name} is owned by {existing_owner!r}, not {owner!r}",
            )
            return False
        changed = False
        labels = meta(ns).setdefault("labels", {})
        for k, v in default_labels.items():
            if labels.get(k) != v:
                labels[k] = v
                changed = True
        if changed:
            self.client.update(ns)
        return True

    # -- rbac ----------------------------------------------------------------

    def _reconcile_service_accounts(self, profile: Resource) -> None:
        ns = name_of(profile)
        for sa_name in (EDITOR_SA, VIEWER_SA):
            sa = {
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": {"name": sa_name, "namespace": ns},
            }
            set_owner(sa, profile)
            try:
                apply.create(self.client, sa)
            except errors.Conflict:
                pass

    def _reconcile_role_bindings(self, profile: Resource) -> None:
        ns = name_of(profile)
        owner = deep_get(profile, "spec", "owner", default={})
        bindings = [
            (EDITOR_SA, CLUSTER_ROLE_EDIT,
             {"kind": "ServiceAccount", "name": EDITOR_SA, "namespace": ns}),
            (VIEWER_SA, CLUSTER_ROLE_VIEW,
             {"kind": "ServiceAccount", "name": VIEWER_SA, "namespace": ns}),
            (ADMIN_BINDING, CLUSTER_ROLE_ADMIN, {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": owner.get("kind", "User"),
                "name": owner.get("name", ""),
            }),
        ]
        for binding_name, role, subject in bindings:
            # The role/user annotations are KFAM's marker for USER bindings
            # (what the contributors view lists); the ServiceAccount
            # bindings must not carry them or default-editor/viewer show up
            # as namespace contributors (caught by the DOM frontend tests).
            annotations = {}
            if subject.get("kind") != "ServiceAccount":
                annotations = {"role": role.removeprefix("kubeflow-"),
                               "user": subject.get("name", "")}
            rb = {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "RoleBinding",
                "metadata": {
                    "name": binding_name,
                    "namespace": ns,
                    "annotations": annotations,
                },
                "roleRef": {
                    "apiGroup": "rbac.authorization.k8s.io",
                    "kind": "ClusterRole",
                    "name": role,
                },
                "subjects": [subject],
            }
            set_owner(rb, profile)
            self._create_or_replace(ROLEBINDING, rb)

    # -- istio ---------------------------------------------------------------

    def _reconcile_authorization_policy(self, profile: Resource) -> None:
        ns = name_of(profile)
        owner = deep_get(profile, "spec", "owner", "name", default="")
        header_value = f"{self.userid_prefix}{owner}"
        policy = {
            "apiVersion": "security.istio.io/v1beta1",
            "kind": "AuthorizationPolicy",
            "metadata": {"name": AUTH_POLICY_NAME, "namespace": ns},
            "spec": {
                "rules": [
                    # Owner traffic, identified by the trusted gateway header.
                    {"when": [{
                        "key": f"request.headers[{self.userid_header}]",
                        "values": [header_value],
                    }]},
                    # In-namespace traffic (sidecar-to-sidecar).
                    {"from": [{"source": {"namespaces": [ns]}}]},
                    # Culling probe: the notebook controller SA may GET the
                    # kernels API (reference :470-488).
                    {
                        "from": [{"source": {
                            "principals": [self.notebook_controller_sa],
                        }}],
                        "to": [{"operation": {
                            "methods": ["GET"],
                            "paths": ["*/api/kernels"],
                        }}],
                    },
                ]
            },
        }
        set_owner(policy, profile)
        self._create_or_replace(AUTHORIZATIONPOLICY, policy)

    # -- quota (the TPU hook) ------------------------------------------------

    def _reconcile_resource_quota(self, profile: Resource) -> None:
        ns = name_of(profile)
        spec = deep_get(profile, "spec", "resourceQuotaSpec", default={}) or {}
        if not spec.get("hard"):
            # No quota requested: remove a previously-managed one.
            try:
                self.client.delete(RESOURCEQUOTA, QUOTA_NAME, ns)
            except errors.NotFound:
                pass
            return
        quota = {
            "apiVersion": "v1",
            "kind": "ResourceQuota",
            "metadata": {"name": QUOTA_NAME, "namespace": ns},
            "spec": spec,
        }
        set_owner(quota, profile)
        self._create_or_replace(RESOURCEQUOTA, quota)

    # -- plugins -------------------------------------------------------------

    def _apply_plugins(self, profile: Resource) -> None:
        for plugin_cfg in deep_get(profile, "spec", "plugins", default=[]) or []:
            kind = plugin_cfg.get("kind", "")
            plugin = self.plugins.get(kind)
            if plugin is None:
                self.recorder.event(
                    profile, "Warning", "UnknownPlugin", f"no plugin {kind!r}"
                )
                continue
            plugin.apply(self.client, profile, plugin_cfg.get("spec", {}) or {})

    def _revoke_plugins(self, profile: Resource) -> None:
        for plugin_cfg in deep_get(profile, "spec", "plugins", default=[]) or []:
            plugin = self.plugins.get(plugin_cfg.get("kind", ""))
            if plugin is not None:
                try:
                    plugin.revoke(
                        self.client, profile, plugin_cfg.get("spec", {}) or {}
                    )
                except Exception:
                    self.recorder.event(
                        profile, "Warning", "PluginRevokeFailed",
                        f"revoke {plugin_cfg.get('kind')} failed",
                    )

    # -- status/helpers ------------------------------------------------------

    def _create_or_replace(self, gvk, desired: Resource) -> None:
        ns = deep_get(desired, "metadata", "namespace")
        name = name_of(desired)
        try:
            current = self.client.get(gvk, name, ns)
        except errors.NotFound:
            apply.create(self.client, desired)
            return
        interesting = ("spec", "roleRef", "subjects")
        if any(current.get(k) != desired.get(k) for k in interesting if k in desired):
            current.update({k: desired[k] for k in interesting if k in desired})
            self.client.update(current)

    def _set_ready(self, profile: Resource) -> None:
        self._set_status(profile, {"status": "Succeeded", "message": ""})

    def _set_failed(self, profile: Resource, message: str) -> None:
        self.recorder.event(profile, "Warning", "ProfileFailed", message,
                            namespace="default")
        self._set_status(profile, {"status": "Failed", "message": message})

    def _set_status(self, profile: Resource, status: dict) -> None:
        # Diff-and-patch the status subresource (runtime/apply.py): only
        # the changed subtree is written, conflict-free.
        from kubeflow_tpu.platform.runtime.apply import patch_status_diff

        patch_status_diff(self.client, PROFILE, profile, status)


def labels_file_watcher(path: str, *, poll_seconds: float = 1.0):
    """Controller runnable: poll the namespace-labels file's mtime and
    trigger a reconcile of every Profile when it changes — the fsnotify
    watch + reconcile-all of the reference (profile_controller.go:368-399).
    mtime polling also covers the ConfigMap symlink-swap dance the
    reference handles via Remove+re-Add."""
    import logging
    import os

    def run(controller) -> None:
        from kubeflow_tpu.platform.runtime import Request as Req

        def stat():
            try:
                st = os.stat(path)
                return (st.st_mtime_ns, st.st_ino)
            except OSError:
                return None

        last = stat()
        while not controller._stop.wait(poll_seconds):
            now = stat()
            if now != last:
                last = now
                try:
                    for p in controller.reconciler.client.list(PROFILE):
                        controller.queue.add(Req("", name_of(p)))
                except Exception:
                    # Transient list failure; the next file change retries.
                    logging.getLogger("kubeflow_tpu.controllers.profile").debug(
                        "labels-file relist failed; next change retries",
                        exc_info=True)

    return run


def make_controller(client, *, heartbeat: bool = False, **kwargs):
    from kubeflow_tpu.platform.runtime import Controller

    shards = kwargs.pop("shards", None)
    reconciler = ProfileReconciler(client, **kwargs)
    runnables = []
    if reconciler.labels_path:
        runnables.append(labels_file_watcher(reconciler.labels_path))
    return Controller(
        "profile-controller",
        reconciler,
        primary=PROFILE,
        resync_period=300.0,
        # Deliberately NO primary informer: a missing Profile CRD must
        # degrade to a retrying raw watch, not a fatal cache-sync failure
        # that takes the whole controller manager down (Controller.start
        # raises on sync timeout).  The raw watch resumes by
        # resourceVersion (_watch_loop), so re-establishments no longer
        # replay every Profile as ADDED anyway.
        runnables=runnables,
        # Heartbeat rides the controller lifecycle: stop_heartbeat on stop
        # drops the ticker AND the registry entry, so a rebuilt controller
        # (tests, leader-election restart) gets a fresh heartbeat instead
        # of the pre-fix wedged Event.
        on_start=(lambda: metrics.start_heartbeat("profile"))
        if heartbeat else None,
        on_stop=(lambda: metrics.stop_heartbeat("profile"))
        if heartbeat else None,
        shards=shards,
    )
