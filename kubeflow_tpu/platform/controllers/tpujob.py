"""TPUJob reconciler: TPUJob CR → gang of per-slice StatefulSets + Service.

The platform's first *training* workload (ROADMAP item 4 — the PR that
welds the repo's two halves together): the notebook reconciler's slice
conventions applied to batch jobs, plus the gang/restart semantics a
multi-slice ``jax.distributed`` job actually needs:

* **Gang creation** — one multi-host worker StatefulSet per ICI slice
  (``replicas = hosts(topology)``, pod ordinal == TPU worker id, Parallel
  pod management), every pod requesting ``google.com/tpu`` chips with the
  accelerator/topology node selectors, all behind ONE headless coordinator
  Service (``<name>-workers``, publishNotReadyAddresses) so worker DNS
  resolves during the rendezvous.
* **The env contract** — TPU_* per-slice bootstrap plus the MEGASCALE_*
  cross-slice identity, built from ``parallel/envspec.py`` — the SAME
  constants ``parallel/dist.py`` discovers with, so controller and trainer
  cannot drift.  ``spec.checkpointDir`` rides along as KFT_CHECKPOINT_DIR
  (the ``train/run.py`` --checkpoint-dir default).
* **All-or-nothing restarts** — any worker pod failing tears down the
  WHOLE generation (every slice's StatefulSet and pods) and recreates it
  under a bumped generation label; a restarted gang resumes from
  ``CheckpointManager.latest_step()`` because the checkpoint dir is stable
  across generations.  ``spec.backoffLimit`` bounds the gang restarts,
  ``restartPolicy: Never`` disables them.
* **Status aggregation** — Pending → Running → Succeeded/Failed/Restarting
  with per-slice ready counts and the restart counter, computed from pod
  phases read through the shard-filterable informer caches.
* **Quota-aware gang queueing** (ROADMAP item 4, the multi-tenant PR) —
  admission is a queue decision over the ``runtime/jobqueue.py`` capacity
  ledger (free chips per profile quota + free topology slots): a gang that
  does not fit WHOLE parks ``Queued`` with a structured ``Unschedulable``
  reason instead of racing its siblings for chips; the queue drains in
  priority-then-FIFO order.  ``spec.priority`` adds preemption: a
  higher-priority head waiter makes the lowest-priority running gang
  checkpoint-then-evict over the PR-9 SIGTERM path — two-phase (mark
  ``Preempting``, wait out the checkpoint grace, then free), and the
  preemptor is never half-admitted.  ``spec.tpu.minSlices`` adds elastic
  capacity: a preempted/shrunk gang resumes the SAME checkpoint at fewer
  slices (the granted width rides as MEGASCALE_NUM_SLICES, so
  ``dist.process_grid`` remaps the dcn(dp) axis for free) and grows back
  when capacity frees.  All decisions are pure functions of watch state —
  under sharded HA every replica computes the same schedule from the
  unsharded queue feed and acts only on owned keys (a victim preempts
  ITSELF; there are no cross-key writes to fence).

Terminal phases are sticky, and a finished gang's StatefulSets are deleted
so the chips free immediately (pods are left for log retrieval, like a
completed Job's).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from kubeflow_tpu.parallel import envspec
from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import tpujob as jobapi
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    POD,
    SERVICE,
    STATEFULSET,
    TPUJOB,
    Resource,
    deep_get,
    meta,
    name_of,
    pod_ready,
    set_owner,
    thaw,
)
from kubeflow_tpu.platform.runtime import EventRecorder, Reconciler, Request, Result
from kubeflow_tpu.platform.runtime import jobqueue as jq
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.runtime import apply
from kubeflow_tpu.platform.runtime.apply import patch_status_diff
from kubeflow_tpu.platform.runtime.flight import shared_pool
from kubeflow_tpu.platform.tpu import SliceSpec

GENERATION_ANNOTATION = "tpujobs.kubeflow.org/generation"

# How long a Preempting gang gets to checkpoint before its chips are
# reclaimed (phase 2 completes early if every worker pod is already gone
# or terminal).  Mirrors the kubelet's terminationGracePeriod role: the
# STS teardown delivers SIGTERM, train/run.py's handler force-saves, and
# this deadline bounds how long the queue waits for it.
DEFAULT_PREEMPTION_GRACE_S = 30.0
# Queued / shrunk jobs poll the ledger on this cadence as a backstop for
# missed kick events — progress must never depend on a watch delta
# arriving (chaos storms drop them; sharded replicas only see owned
# deltas on the controller informers).
DEFAULT_QUEUE_POLL_S = 1.0


class _SliceNameConflict(Exception):
    """A slice StatefulSet name is already owned by a different workload."""


class TPUJobReconciler(Reconciler):
    def __init__(self, client, *, cluster_domain: Optional[str] = None,
                 informers: Optional[dict] = None,
                 queue: Optional[jq.JobQueue] = None,
                 preemption_grace: Optional[float] = None,
                 queue_poll: Optional[float] = None):
        self.client = client
        # GVK -> Informer (make_controller wires them): pod/STS reads come
        # from the indexed caches — shard-filtered under sharded HA, so a
        # replica aggregates status only for gangs it owns.  Absent (bare
        # unit-test construction), reads fall back to client lists.
        self.informers: dict = informers or {}
        self.recorder = EventRecorder(client, "tpujob-controller")
        self.flights = shared_pool()
        self.cluster_domain = cluster_domain or config.env(
            "CLUSTER_DOMAIN", "cluster.local")
        # The admission ledger.  make_controller passes an informer-fed
        # instance; bare construction gets a client-backed one that
        # rebuilds from lists per decision (unit-test mode).
        self.queue = queue if queue is not None else jq.JobQueue(client)
        self.preemption_grace = (
            preemption_grace if preemption_grace is not None
            else config.env_float("TPUJOB_PREEMPTION_GRACE_SECONDS",
                                  DEFAULT_PREEMPTION_GRACE_S))
        self.queue_poll = (
            queue_poll if queue_poll is not None
            else config.env_float("TPUJOB_QUEUE_POLL_SECONDS",
                                  DEFAULT_QUEUE_POLL_S))

    # -- cache-backed reads ---------------------------------------------------

    def _cached_get(self, gvk, name: str, ns: str) -> Optional[Resource]:
        from kubeflow_tpu.platform.runtime.informer import cache_or_client_get

        return cache_or_client_get(self.informers.get(gvk), self.client,
                                   gvk, name, ns)

    def _pods_of(self, ns: str, name: str) -> List[Resource]:
        inf = self.informers.get(POD)
        if inf is not None:
            return inf.index_list("tpujob", f"{ns}/{name}")
        return self.client.list(
            POD, ns, label_selector={jobapi.LABEL_TPUJOB_NAME: name})

    def _stses_of(self, ns: str, name: str) -> List[Resource]:
        inf = self.informers.get(STATEFULSET)
        if inf is not None:
            return inf.index_list("tpujob", f"{ns}/{name}")
        return self.client.list(
            STATEFULSET, ns,
            label_selector={jobapi.LABEL_TPUJOB_NAME: name})

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            job = self.client.get(TPUJOB, req.name, req.namespace)
        except errors.NotFound:
            # ownerReference GC tears the gang down with the CR.
            return None

        try:
            jobapi.validate(job)
        except jobapi.ValidationError as e:
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "InvalidSpec", "message": str(e),
            }]}
            if job.get("status") != status:
                self.recorder.event(job, "Warning", "InvalidTPUJob", str(e))
                patch_status_diff(self.client, TPUJOB, job, status)
            return None

        ns, name = meta(job)["namespace"], name_of(job)
        if jobapi.phase_of(job) in jobapi.TERMINAL_PHASES:
            # Terminal is sticky; a new run is a new CR.  But finish any
            # chip-freeing teardown a transient fault interrupted after
            # the terminal status landed — otherwise the StatefulSets
            # would hold their TPU hosts forever.
            self.queue.forget(ns, name)
            if self._stses_of(ns, name):
                self._teardown_gang(ns, name, delete_pods=False)
            return None

        # Read-your-writes for the ledger: the clientless queue rebuilds
        # from lists; the informer-fed one just folds in THIS job's live
        # truth (our own status writes may outrun the watch stream).
        self.queue.ensure_fresh()
        self.queue.observe(job)
        spec = jobapi.tpu_slice(job)
        phase = jobapi.phase_of(job)

        if phase == jobapi.PHASE_PREEMPTING:
            return self._finish_preemption(job, spec)

        alloc = jobapi.allocated_slices(job)
        if alloc is None:
            # Not holding chips: admission is a queue decision.  Either
            # the whole gang is granted (possibly elastically, at
            # minSlices <= k <= slices) and we fall through to create it
            # THIS reconcile, or the job parks Queued with a structured
            # reason and polls the ledger.
            admitted = self._admission(job, spec)
            if isinstance(admitted, Result):
                return admitted
            job, alloc = admitted

        generation = jobapi.generation_of(job)

        # A higher-priority head waiter (or a shrunk node pool) claimed
        # this gang's chips: begin the two-phase checkpoint-then-evict.
        yielding = self.queue.should_yield(ns, name)
        if yielding is not None and phase in (
                jobapi.PHASE_RUNNING, jobapi.PHASE_PENDING,
                jobapi.PHASE_RESTARTING):
            return self._begin_preemption(job, spec, yielding)

        # Elastic grow-back: a shrunk Running gang resizes up when
        # capacity frees and nothing is waiting (waiters first).
        if phase == jobapi.PHASE_RUNNING and alloc < spec.num_slices:
            grow = self.queue.grow_target(ns, name)
            if grow is not None and grow > alloc:
                return self._begin_resize(job, alloc, grow)

        # Conflict-check every slice name BEFORE writing anything: a
        # partial gang would hold TPU hosts forever at the barrier.
        try:
            for s in range(alloc):
                self._check_sts_ownership(ns, name,
                                          self.slice_sts_name(name, s))
        except _SliceNameConflict as e:
            self.recorder.event(job, "Warning", "SliceNameConflict", str(e))
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "SliceNameConflict", "message": str(e),
            }]}
            if job.get("status") != status:
                patch_status_diff(self.client, TPUJOB, job, status)
            return None

        pods = self._pods_of(ns, name)
        current, stale = self._split_by_generation(pods, generation)
        # Stragglers of a torn-down generation: GC opportunistically so
        # they never pollute the new gang's aggregation.  Worker names are
        # REUSED across generations (STS ordinals), so a lagging informer
        # cache can present a just-recreated current-generation pod under
        # a stale object — re-check generation on a fresh GET before the
        # delete, or the GC kills a live worker of the new gang.
        for pod in stale:
            pod_name = name_of(pod)
            try:
                live = self.client.get(POD, pod_name, ns)
            except errors.NotFound:
                continue
            except errors.ApiError:
                continue  # retried on the requeue this reconcile gets
            live_gen = deep_get(live, "metadata", "labels",
                                jobapi.LABEL_GENERATION)
            if live_gen == str(generation):
                continue  # cache lag: the name already belongs to this gang
            try:
                self.client.delete(POD, pod_name, ns)
            except errors.ApiError:
                pass

        failed = [p for p in current
                  if deep_get(p, "status", "phase") == "Failed"]
        if failed:
            return self._handle_gang_failure(job, spec, generation, failed)

        self._reconcile_statefulsets(job, spec, generation, alloc)
        self._reconcile_headless_service(job)
        self._update_status(job, spec, generation, alloc, current)
        if alloc < spec.num_slices:
            # Shrunk gang: poll for grow-back capacity (kick events are
            # the fast path, this is the guarantee).
            return Result(requeue_after=max(self.queue_poll, 2.0))
        return None

    # -- admission / queueing -------------------------------------------------

    def _admission(self, job: Resource, spec: SliceSpec):
        """Decide admission for a job holding no chips.  Returns a
        ``Result`` (parked Queued, polling) or ``(fresh_job, alloc)``
        after committing the claim — allocatedSlices is written BEFORE
        any StatefulSet exists, so a rebuilt ledger (restart, other
        replica) always accounts a gang that might be mid-creation and
        the fleet can never oversubscribe through a crash window."""
        ns, name = meta(job)["namespace"], name_of(job)
        decision = self.queue.decide(ns, name)
        if decision.action == "admit":
            # Commit-time confirm under the admission mutex: the fast
            # decide above ran on watch state, which a fault storm can
            # hold seconds stale — two workers deciding off the same
            # stale snapshot would both admit into one free slot.  The
            # confirm rebuilds from LIVE lists and the commit lands
            # inside the same critical section, so the next confirm is
            # guaranteed to see it.
            with self.queue.admission_mutex:
                decision = self.queue.confirm(self.client, ns, name)
                if decision.action == "admit":
                    queued_since = jobapi.queued_at(job)
                    if queued_since is not None:
                        metrics.tpujob_queue_wait_seconds.observe(
                            max(0.0, time.time() - queued_since))
                    # Causal journey: ONE admission_queue span per
                    # admission — queuedAt → granted for a parked job,
                    # zero-length inside this reconcile for a job that
                    # fit immediately (the critical-path analyzer
                    # carves it out of the reconcile either way, so
                    # submit→Running decomposes with exactly one
                    # admission segment; conformance pins it).
                    from kubeflow_tpu.telemetry import causal

                    jctx = causal.from_object(job)
                    if jctx is not None:
                        admit_ts = time.time()
                        causal.record(
                            "admission_queue", trace_id=jctx.trace_id,
                            parent_span_id=jctx.span_id,
                            segment="admission_queue",
                            start_ts=(queued_since
                                      if queued_since is not None
                                      else admit_ts),
                            end_ts=admit_ts, object=name,
                            slices=decision.slices)
                    # Re-admissions (a preemption wrote status.generation
                    # before) start a NEW gang generation; a first-ever
                    # admission keeps generation == restarts so a legacy
                    # pre-queue job's live workers never read as stale.
                    prior_gen = deep_get(job, "status", "generation")
                    new_gen = (jobapi.generation_of(job) + 1
                               if prior_gen is not None
                               else jobapi.generation_of(job))
                    status = {
                        "phase": jobapi.PHASE_PENDING,
                        "restarts": jobapi.restarts_of(job),
                        "generation": new_gen,
                        "allocatedSlices": decision.slices,
                        "slices": self._slice_counts_named(
                            name, spec, {}, decision.slices),
                    }
                    patch_status_diff(self.client, TPUJOB, job, status)
                    fresh = self.client.get(TPUJOB, name, ns)
                    self.queue.observe(fresh)
        if decision.action == "admit":
            self.recorder.event(
                job, "Normal", "Admitted",
                f"granted {decision.slices}/{spec.num_slices} slice(s) "
                f"(generation {new_gen})"
                + (" — elastic" if decision.slices < spec.num_slices
                   else ""))
            return fresh, decision.slices
        if decision.action != "wait":
            # "admitted": the live rebuild found allocatedSlices already
            # set — this reconcile read the job through a lagging cache.
            # "unknown": the entry vanished mid-decision (delete race).
            # Neither is a reason to park a possibly-running gang under
            # a Queued status; re-read and retry shortly.
            if decision.action == "admitted":
                fresh = self.client.get(TPUJOB, name, ns)
                self.queue.observe(fresh)
                alloc = jobapi.allocated_slices(fresh)
                if alloc is not None:
                    return fresh, alloc
            return Result(requeue_after=min(self.queue_poll, 0.25))
        # Park Queued with the structured reason.  The Unschedulable
        # condition carries the human-readable detail; status.reason is
        # the REASON printer column.
        queued_since = jobapi.queued_at(job)
        status = {
            "phase": jobapi.PHASE_QUEUED,
            "restarts": jobapi.restarts_of(job),
            "reason": decision.reason,
            "queuedAt": (queued_since if queued_since is not None
                         else round(time.time(), 3)),
            "conditions": [{
                "type": "Unschedulable", "status": "True",
                "reason": decision.reason, "message": decision.message,
            }],
        }
        prior_gen = deep_get(job, "status", "generation")
        if prior_gen is not None:
            status["generation"] = int(prior_gen)
        if deep_get(job, "status", "reason") != decision.reason:
            self.recorder.event(
                job, "Normal", "Queued",
                f"{decision.reason}: {decision.message}")
        if job.get("status") != status:
            patch_status_diff(self.client, TPUJOB, job, status)
            self.queue.observe(self.client.get(TPUJOB, name, ns))
        return Result(requeue_after=self.queue_poll)

    # -- preemption (two-phase checkpoint-then-evict) -------------------------

    def _begin_preemption(self, job: Resource, spec: SliceSpec,
                          yielding) -> Optional[Result]:
        """Phase 1: commit the Preempting intent, then tear down the
        slice StatefulSets — on a real cluster the cascade delivers
        SIGTERM + grace to every worker, and train/run.py's handler
        force-saves a checkpoint (the provably-safe PR-9 path).  The
        chips stay CHARGED to this job (allocatedSlices kept) until
        phase 2 confirms the drain, so the preemptor can never be
        half-admitted into capacity the victim still holds."""
        by, why = yielding
        ns, name = meta(job)["namespace"], name_of(job)
        status = dict(job.get("status") or {})
        status.update({
            "phase": jobapi.PHASE_PREEMPTING,
            "reason": (jq.REASON_PREEMPTED if why == "priority"
                       else "CapacityShrunk"),
            "preemption": {"by": by, "reason": why,
                           "at": round(time.time(), 3)},
            "conditions": [{
                "type": "Preempted", "status": "True",
                "reason": "PreemptedBy" if why == "priority"
                          else "CapacityShrunk",
                "message": (f"checkpoint-then-evict for {by}" if by
                            else "node pool shrank under the gang"),
            }],
        })
        patch_status_diff(self.client, TPUJOB, job, status)
        metrics.tpujob_preemptions_total.labels(reason=why).inc()
        self.recorder.event(
            job, "Warning", "Preempting",
            (f"higher-priority job {by} claims this gang's chips; "
             if by else "node pool shrank; ")
            + "checkpointing then releasing "
            f"{jobapi.allocated_slices(job)} slice(s)")
        self.queue.observe(self.client.get(TPUJOB, name, ns))
        # The SIGTERM: tear down the StatefulSets only — worker pods ride
        # out their grace period checkpointing.
        for sts in self._stses_of(ns, name):
            try:
                self.client.delete(STATEFULSET, name_of(sts), ns)
            except errors.NotFound:
                pass
        return Result(requeue_after=min(self.queue_poll, 0.25))

    def _begin_resize(self, job: Resource, alloc: int,
                      target: int) -> Optional[Result]:
        """Elastic grow-back = a voluntary self-preemption: same graceful
        drain (checkpoint over SIGTERM), but phase 2 re-admits at the
        recomputed width instead of parking Queued.  Never consumes
        backoffLimit — a resize is not a failure."""
        ns, name = meta(job)["namespace"], name_of(job)
        status = dict(job.get("status") or {})
        status.update({
            "phase": jobapi.PHASE_PREEMPTING,
            "reason": jq.REASON_RESIZING,
            "resize": {"to": target, "at": round(time.time(), 3)},
            "conditions": [{
                "type": "Preempted", "status": "True",
                "reason": jq.REASON_RESIZING,
                "message": f"growing from {alloc} to {target} slice(s); "
                           "checkpointing for the restart",
            }],
        })
        patch_status_diff(self.client, TPUJOB, job, status)
        self.recorder.event(
            job, "Normal", "Resizing",
            f"capacity freed: growing from {alloc} to {target} slice(s) "
            "via checkpoint-restart")
        self.queue.observe(self.client.get(TPUJOB, name, ns))
        for sts in self._stses_of(ns, name):
            try:
                self.client.delete(STATEFULSET, name_of(sts), ns)
            except errors.NotFound:
                pass
        return Result(requeue_after=min(self.queue_poll, 0.25))

    def _finish_preemption(self, job: Resource,
                           spec: SliceSpec) -> Optional[Result]:
        """Phase 2: wait for the checkpoint drain — every current-
        generation worker pod gone/terminal, or the grace deadline — then
        reclaim the chips.  A preemption parks the job back in the queue
        (it re-admits elastically when capacity allows); a resize
        re-admits immediately at the recomputed width."""
        ns, name = meta(job)["namespace"], name_of(job)
        generation = jobapi.generation_of(job)
        intent = (deep_get(job, "status", "resize")
                  or deep_get(job, "status", "preemption") or {})
        started = float(intent.get("at") or 0.0)
        deadline = started + self.preemption_grace
        pods = self._pods_of(ns, name)
        current, _stale = self._split_by_generation(pods, generation)
        active = [p for p in current
                  if deep_get(p, "status", "phase")
                  not in ("Succeeded", "Failed")]
        now = time.time()
        if active and now < deadline:
            return Result(requeue_after=min(
                max(deadline - now, 0.05), 0.25))
        # Drain confirmed (or deadline passed): clear the slate.
        self._teardown_gang(ns, name, delete_pods=True)
        resize = deep_get(job, "status", "resize")
        if resize is not None:
            # Recompute against the CURRENT ledger — capacity may have
            # moved (or a waiter arrived) during the drain.  The stored
            # resize.to is intent, not entitlement: a None grow_target
            # now means the growth lost its window, so the gang simply
            # recreates at the width it already holds.  Never below the
            # held width, never above the spec.
            alloc = jobapi.allocated_slices(job) or 1
            target = self.queue.grow_target(ns, name)
            new_alloc = min(max(target if target is not None else alloc,
                                alloc), spec.num_slices)
            status = {
                "phase": jobapi.PHASE_PENDING,
                "restarts": jobapi.restarts_of(job),
                "generation": generation + 1,
                "allocatedSlices": new_alloc,
                "slices": self._slice_counts_named(
                    name, spec, {}, new_alloc),
            }
            patch_status_diff(self.client, TPUJOB, job, status)
        else:
            status = {
                "phase": jobapi.PHASE_QUEUED,
                "restarts": jobapi.restarts_of(job),
                "generation": generation,
                "reason": jq.REASON_PREEMPTED,
                "queuedAt": round(time.time(), 3),
                "conditions": [{
                    "type": "Unschedulable", "status": "True",
                    "reason": jq.REASON_PREEMPTED,
                    "message": "gang evicted after checkpoint; waiting "
                               "to resume (elastically at minSlices "
                               "when capacity allows)",
                }],
            }
            patch_status_diff(self.client, TPUJOB, job, status)
            self.recorder.event(
                job, "Normal", "PreemptionComplete",
                "checkpoint drain finished; chips released, job "
                "re-queued for elastic resume")
        self.queue.observe(self.client.get(TPUJOB, name, ns))
        return Result(requeue_after=self.queue_poll)

    # -- gang restart ---------------------------------------------------------

    def _handle_gang_failure(self, job: Resource, spec: SliceSpec,
                             generation: int,
                             failed: List[Resource]) -> Optional[Result]:
        """All-or-nothing: one failed worker condemns the whole generation.
        Either recreate the gang under generation+1 (resume comes free:
        same checkpoint dir, ``latest_step()`` in the trainer) or, with the
        backoff exhausted / restartPolicy Never, go terminally Failed."""
        ns, name = meta(job)["namespace"], name_of(job)
        who = ", ".join(sorted(name_of(p) for p in failed))
        restarts = jobapi.restarts_of(job)
        alloc = jobapi.allocated_slices(job) or spec.num_slices
        exhausted = (jobapi.restart_policy(job) == "Never"
                     or restarts >= jobapi.backoff_limit(job))
        if exhausted:
            self._teardown_gang(ns, name, delete_pods=False)
            self.recorder.event(
                job, "Warning", "GangFailed",
                f"worker pod(s) {who} failed; restartPolicy="
                f"{jobapi.restart_policy(job)} backoffLimit="
                f"{jobapi.backoff_limit(job)} exhausted after "
                f"{restarts} restart(s)")
            status = {
                "phase": jobapi.PHASE_FAILED,
                "restarts": restarts,
                "slices": self._slice_counts_named(name, spec, {}, alloc),
                "conditions": [{
                    "type": "Failed", "status": "True",
                    "reason": "BackoffLimitExceeded",
                    "message": f"worker pod(s) {who} failed",
                }],
            }
            if deep_get(job, "status", "generation") is not None:
                status["generation"] = generation
            patch_status_diff(self.client, TPUJOB, job, status)
            # Terminal Failed frees the chips in the ledger — THIS is why
            # a crashlooping high-priority job can never starve the
            # queue: backoffLimit turns it terminal and the next waiter
            # admits into the freed capacity.
            self.queue.observe(self.client.get(TPUJOB, name, ns))
            return None
        self.recorder.event(
            job, "Warning", "GangRestart",
            f"worker pod(s) {who} failed; tearing down all "
            f"{alloc} slice(s) and restarting the gang "
            f"(generation {generation + 1})")
        status = {
            "phase": jobapi.PHASE_RESTARTING,
            "restarts": restarts + 1,
            "slices": self._slice_counts_named(name, spec, {}, alloc),
        }
        if deep_get(job, "status", "generation") is not None:
            # Failure restarts bump BOTH counters; resizes/re-admissions
            # bump only the generation (they never eat backoffLimit).
            # The gang KEEPS its allocation across a restart — a crash is
            # not a queue event, and dropping allocatedSlices here would
            # send the job back through admission (racing the queue for
            # chips it already holds).
            status["generation"] = generation + 1
            status["allocatedSlices"] = alloc
        # Persist the bumped counter BEFORE tearing anything down: the
        # teardown deletes the Failed pods (the evidence), so a crash or
        # transient status-write fault after it would replay this restart
        # at the SAME generation — an unaccounted restart that lets a
        # crashlooping job ride past backoffLimit forever.  With restarts
        # committed first, a retry resumes through the normal path (old-
        # generation pods/STSes read as stale and are GC'd/recreated).
        patch_status_diff(self.client, TPUJOB, job, status)
        metrics.tpujob_restarts_total.inc()
        self._teardown_gang(ns, name, delete_pods=True)
        # The deletion events re-enqueue this key; the next reconcile
        # creates the generation+1 StatefulSets against a clean slate.
        return None

    def _teardown_gang(self, ns: str, name: str, *,
                       delete_pods: bool) -> None:
        """Delete every slice StatefulSet (and, on a restart, every worker
        pod so the new generation starts clean; a terminally-Failed job
        keeps its pods for post-mortem logs, like a finished Job's)."""
        for sts in self._stses_of(ns, name):
            try:
                # Orphan on the keep-pods path: the default Background
                # propagation would cascade to the STS-owned worker pods
                # on a real cluster, silently breaking the kept-for-logs
                # contract (a restart deletes the pods itself below).
                self.client.delete(
                    STATEFULSET, name_of(sts), ns,
                    propagation="Background" if delete_pods else "Orphan")
            except errors.NotFound:
                pass
        if delete_pods:
            for pod in self._pods_of(ns, name):
                try:
                    self.client.delete(POD, name_of(pod), ns)
                except errors.ApiError:
                    pass

    # -- statefulsets ---------------------------------------------------------

    @staticmethod
    def slice_sts_name(name: str, slice_idx: int) -> str:
        """Slice 0 keeps the bare job name — worker ``<name>-0`` is the
        MEGASCALE coordinator, stable across generations — and later
        slices get ``<name>-s<i>``, the notebook reconciler's multislice
        layout (GKE's one-workload-per-slice shape)."""
        return name if slice_idx == 0 else f"{name}-s{slice_idx}"

    def generate_statefulset(self, job: Resource, slice_idx: int = 0,
                             generation: int = 0,
                             num_slices: Optional[int] = None) -> Resource:
        """``num_slices`` is the GRANTED gang width (elastic admission may
        run fewer slices than spec.tpu.slices); default = the full spec,
        preserving the pre-queue contract for direct callers."""
        ns, name = meta(job)["namespace"], name_of(job)
        spec = jobapi.tpu_slice(job)
        if num_slices is None:
            num_slices = spec.num_slices
        sts_name = self.slice_sts_name(name, slice_idx)

        pod_spec = thaw(
            deep_get(job, "spec", "template", "spec", default={}))
        containers = pod_spec.get("containers") or [{}]
        main = containers[0]
        main.setdefault("name", "worker")
        self._inject_tpu(pod_spec, main, ns, name, spec, slice_idx,
                         num_slices)
        ckpt = jobapi.checkpoint_dir(job)
        if ckpt:
            env = main.setdefault("env", [])
            if not any(e.get("name") == envspec.ENV_KFT_CHECKPOINT_DIR
                       for e in env):
                env.append({"name": envspec.ENV_KFT_CHECKPOINT_DIR,
                            "value": ckpt})

        labels = {
            "statefulset": sts_name,
            jobapi.LABEL_TPUJOB_NAME: name,
            jobapi.LABEL_TPUJOB_WORKER: "true",
            jobapi.LABEL_GENERATION: str(generation),
        }
        sts = {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sts_name,
                "namespace": ns,
                "labels": dict(labels),
                "annotations": {GENERATION_ANNOTATION: str(generation)},
            },
            "spec": {
                "replicas": spec.num_hosts,
                "serviceName": f"{name}-workers",
                "podManagementPolicy": "Parallel",  # the whole gang at once
                "selector": {"matchLabels": {"statefulset": sts_name}},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": pod_spec,
                },
            },
        }
        set_owner(sts, job)
        return sts

    def _inject_tpu(self, pod_spec: dict, container: dict, ns: str,
                    name: str, spec: SliceSpec, slice_idx: int,
                    num_slices: int) -> None:
        resources = container.setdefault("resources", {})
        resources.setdefault("limits", {}).update(spec.pod_resources())
        resources.setdefault("requests", {}).update(spec.pod_resources())
        pod_spec.setdefault("nodeSelector", {}).update(spec.node_selectors())
        sts_name = self.slice_sts_name(name, slice_idx)
        hostnames = ",".join(
            f"{sts_name}-{i}.{name}-workers.{ns}.svc.{self.cluster_domain}"
            for i in range(spec.num_hosts)
        )
        env = container.setdefault("env", [])
        have = {e.get("name") for e in env}
        # Per-slice libtpu bootstrap + cross-slice MEGASCALE identity, all
        # built by the shared envspec helpers.  Unlike the notebook path,
        # MEGASCALE_* is injected even at num_slices=1: a TPUJob's trainer
        # always runs dist.initialize_from_env, and the uniform contract
        # keeps the round-trip test one shape.  MEGASCALE_NUM_SLICES is
        # the GRANTED width — an elastically-shrunk gang's trainer sees a
        # smaller dcn(dp) axis through dist.process_grid and resumes the
        # same checkpoint at fewer slices; KFT_SPEC_SLICES rides along so
        # it can report it is running shrunk (envspec.elastic_env).
        injected = envspec.tpu_bootstrap_env(
            topology=spec.topology,
            accelerator=spec.accelerator.name,
            chips=spec.chips,
            chips_per_host=spec.chips_per_pod,
            num_hosts=spec.num_hosts,
            hostnames=hostnames,
        ) + envspec.megascale_env(
            slice_idx, num_slices,
            f"{name}-0.{name}-workers.{ns}.svc.{self.cluster_domain}"
        ) + envspec.elastic_env(spec.num_slices)
        env.extend(e for e in injected if e["name"] not in have)

    def _check_sts_ownership(self, ns: str, job_name: str,
                             sts_name: str) -> None:
        current = self._cached_get(STATEFULSET, sts_name, ns)
        if current is None:
            return
        owner = deep_get(current, "metadata", "labels",
                         jobapi.LABEL_TPUJOB_NAME)
        if owner != job_name:
            raise _SliceNameConflict(
                f"StatefulSet {ns}/{sts_name} belongs to "
                f"{'TPUJob ' + owner if owner else 'another workload'}, "
                f"not TPUJob {job_name}; rename one of them")

    def _reconcile_statefulsets(self, job: Resource, spec: SliceSpec,
                                generation: int, alloc: int) -> None:
        """Gang-create: every missing slice StatefulSet of the CURRENT
        generation, concurrently (independent names, one owner), at the
        GRANTED width ``alloc``.  A leftover from an older generation (a
        teardown delete that lost a race) is deleted and recreated."""
        ns, name = meta(job)["namespace"], name_of(job)
        created = self.flights.run([
            (lambda s=s: self._reconcile_one_statefulset(
                job, s, generation, alloc))
            for s in range(alloc)
        ])
        if any(created):
            self.recorder.event(
                job, "Normal", "GangCreated",
                f"created {alloc} slice StatefulSet(s) x "
                f"{spec.num_hosts} worker(s) (generation {generation})")

    def _reconcile_one_statefulset(self, job: Resource, slice_idx: int,
                                   generation: int, alloc: int) -> bool:
        """Returns True when this pass created the slice's StatefulSet."""
        desired = self.generate_statefulset(job, slice_idx, generation,
                                            num_slices=alloc)
        ns, name = meta(desired)["namespace"], name_of(desired)
        current = self._cached_get(STATEFULSET, name, ns)
        if current is not None:
            live_gen = deep_get(current, "metadata", "annotations",
                                GENERATION_ANNOTATION)
            if live_gen == str(generation):
                return False
            # Older generation still standing (teardown raced a transient
            # delete failure): clear it now, recreate below.
            try:
                self.client.delete(STATEFULSET, name, ns)
            except errors.NotFound:
                pass
        try:
            apply.create(self.client, desired)
            return True
        except errors.AlreadyExists:
            # Cache lag on a just-created STS — or an injected/raced 409
            # whose create never landed.  Verify with a fresh GET: present
            # means someone (us, a moment ago) created it; absent means
            # the create really failed, so raise for a backoff requeue
            # instead of silently parking the slice until resync.
            try:
                self.client.get(STATEFULSET, name, ns)
            except errors.NotFound:
                raise
            return False

    # -- coordinator service --------------------------------------------------

    def generate_headless_service(self, job: Resource) -> Resource:
        ns, name = meta(job)["namespace"], name_of(job)
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{name}-workers", "namespace": ns,
                         "labels": {jobapi.LABEL_TPUJOB_NAME: name}},
            "spec": {
                "clusterIP": "None",
                # Worker DNS must resolve BEFORE readiness: the
                # jax.distributed rendezvous happens during bring-up.
                "publishNotReadyAddresses": True,
                # One governing service spans every slice's StatefulSet —
                # cross-slice (DCN) coordinator DNS resolves through it.
                "selector": {jobapi.LABEL_TPUJOB_NAME: name},
                "ports": [{"name": "coordinator",
                           "port": envspec.DEFAULT_COORDINATOR_PORT,
                           "protocol": "TCP"}],
            },
        }
        set_owner(svc, job)
        return svc

    def _reconcile_headless_service(self, job: Resource) -> None:
        desired = self.generate_headless_service(job)
        ns, name = meta(desired)["namespace"], name_of(desired)
        if self._cached_get(SERVICE, name, ns) is not None:
            return  # spec is generation-invariant; nothing to update
        try:
            apply.create(self.client, desired)
        except errors.AlreadyExists:
            pass

    # -- status ---------------------------------------------------------------

    @staticmethod
    def _split_by_generation(pods: List[Resource], generation: int):
        current, stale = [], []
        for pod in pods:
            gen = deep_get(pod, "metadata", "labels",
                           jobapi.LABEL_GENERATION)
            (current if gen == str(generation) else stale).append(pod)
        return current, stale

    def _update_status(self, job: Resource, spec: SliceSpec,
                       generation: int, alloc: int,
                       current: List[Resource]) -> None:
        ns, name = meta(job)["namespace"], name_of(job)
        expected = [
            f"{self.slice_sts_name(name, s)}-{i}"
            for s in range(alloc)
            for i in range(spec.num_hosts)
        ]
        by_name = {name_of(p): p for p in current}
        phases = {n: deep_get(by_name[n], "status", "phase")
                  for n in expected if n in by_name}
        succeeded = sum(1 for p in phases.values() if p == "Succeeded")
        ready = sum(1 for n in expected
                    if n in by_name and pod_ready(by_name[n]))

        if expected and succeeded == len(expected):
            phase = jobapi.PHASE_SUCCEEDED
        elif expected and ready + succeeded == len(expected):
            # Workers finish at slightly different times (the collective
            # tears down rank by rank): a pod that already exited 0 is no
            # longer Ready but must keep counting toward Running, or a
            # completing job would read as Pending/Restarting for its last
            # few seconds.
            phase = jobapi.PHASE_RUNNING
        elif jobapi.restarts_of(job) > 0:
            phase = jobapi.PHASE_RESTARTING
        else:
            phase = jobapi.PHASE_PENDING

        status: dict = {
            "phase": phase,
            "restarts": jobapi.restarts_of(job),
            "slices": self._slice_counts_named(name, spec, by_name, alloc),
        }
        if deep_get(job, "status", "generation") is not None:
            status["generation"] = generation
            status["allocatedSlices"] = alloc
        if job.get("status") != status:
            patch_status_diff(self.client, TPUJOB, job, status)
        if phase == jobapi.PHASE_SUCCEEDED:
            # Terminal phase committed; NOW free the chips (keep the
            # Succeeded pods for logs).  The reverse order let a transient
            # fault on the status write recreate the finished gang: with
            # the STSes already gone and the stored phase still Running,
            # the retry reached _reconcile_statefulsets first.  If THIS
            # teardown faults instead, the terminal-sticky branch in
            # reconcile() finishes the sweep.
            self._teardown_gang(ns, name, delete_pods=False)
            self.queue.observe(self.client.get(TPUJOB, name, ns))
            self.recorder.event(
                job, "Normal", "JobSucceeded",
                f"all {len(expected)} worker(s) across {alloc} "
                f"slice(s) succeeded after "
                f"{jobapi.restarts_of(job)} restart(s)")

    def _slice_counts_named(self, name: str, spec: SliceSpec,
                            by_name: Dict[str, Resource],
                            alloc: Optional[int] = None) -> List[dict]:
        out = []
        for s in range(alloc if alloc is not None else spec.num_slices):
            sts = self.slice_sts_name(name, s)
            ready = sum(
                1 for i in range(spec.num_hosts)
                if f"{sts}-{i}" in by_name
                and pod_ready(by_name[f"{sts}-{i}"]))
            out.append({"slice": s, "ready": ready,
                        "total": spec.num_hosts})
        return out


# -- watch mappers / indexers -------------------------------------------------


def pods_to_tpujob_requests(obj: Resource) -> List[Request]:
    """Watch mapper: pod events → owning TPUJob (by tpujob-name label)."""
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    job = labels.get(jobapi.LABEL_TPUJOB_NAME)
    if not job:
        return []
    return [Request(deep_get(obj, "metadata", "namespace", default=""), job)]


def _job_label_index(obj: Resource) -> List[str]:
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    job = labels.get(jobapi.LABEL_TPUJOB_NAME)
    ns = deep_get(obj, "metadata", "namespace", default="")
    return [f"{ns}/{job}"] if job else []


def make_controller(client, **kwargs):
    from kubeflow_tpu.platform.k8s.types import (
        INFERENCESERVICE,
        NODE,
        RESOURCEQUOTA,
    )
    from kubeflow_tpu.platform.runtime import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer

    # Sharded HA: same contract as the other four controllers — the
    # coordinator shard-filters these informers (a worker pod is cached
    # iff its owning job's key is owned) and the FencedClient proves every
    # gang write against the key's shard lease.
    shards = kwargs.pop("shards", None)
    informers = {
        TPUJOB: Informer(client, TPUJOB),
        POD: Informer(client, POD, indexers={"tpujob": _job_label_index}),
        STATEFULSET: Informer(client, STATEFULSET,
                              indexers={"tpujob": _job_label_index}),
        SERVICE: Informer(client, SERVICE),
    }
    # The admission ledger's feed is deliberately UNSHARDED (and therefore
    # kept out of the controller's informer dict, whose admit filters the
    # coordinator rewires): the queue is a global priority-then-FIFO order
    # over every job + quota + node, and each replica must compute the
    # SAME schedule to act consistently on the keys it owns.  Low churn:
    # the job feed is one watch of a bounded CR kind, quotas and nodes are
    # near-static.
    queue = jq.JobQueue()
    queue.informer_backed = True
    queue_informers = {
        TPUJOB: Informer(client, TPUJOB),
        RESOURCEQUOTA: Informer(client, RESOURCEQUOTA),
        NODE: Informer(client, NODE),
        # Serving shares the chip ledger (docs/serving.md "One quota
        # truth"): InferenceService replica targets are declared charges,
        # so a gang is never promised chips a model server holds.
        INFERENCESERVICE: Informer(client, INFERENCESERVICE),
    }

    def _on_job_delta(etype, obj):
        if etype == "DELETED":
            queue.forget(deep_get(obj, "metadata", "namespace",
                                  default="") or "",
                         name_of(obj))
        else:
            queue.observe(obj)

    queue_informers[TPUJOB].add_handler(_on_job_delta)

    def _on_service_delta(etype, obj):
        ns = deep_get(obj, "metadata", "namespace", default="") or ""
        if etype == "DELETED":
            queue.forget_service(ns, name_of(obj))
        else:
            queue.observe_service(obj)

    queue_informers[INFERENCESERVICE].add_handler(_on_service_delta)
    queue_informers[RESOURCEQUOTA].add_handler(
        lambda _e, _o: queue.set_quotas(
            queue_informers[RESOURCEQUOTA].list()))
    queue_informers[NODE].add_handler(
        lambda _e, _o: queue.set_nodes(queue_informers[NODE].list()))

    reconciler = TPUJobReconciler(client, informers=informers,
                                  queue=queue, **kwargs)

    def on_start():
        metrics.register_tpujob_collector(client)
        jq.register_debug_queue(queue)
        for informer in queue_informers.values():
            informer.start()
        for informer in queue_informers.values():
            # Best-effort: an unsynced ledger degrades to permissive
            # admission (exactly the pre-queue behavior) until the feed
            # lands — never a startup failure.
            informer.wait_for_sync(30.0)

    def on_stop():
        metrics.register_tpujob_collector(None)
        jq.register_debug_queue(None)
        for informer in queue_informers.values():
            informer.stop()

    ctrl = Controller(
        "tpujob-controller",
        reconciler,
        primary=TPUJOB,
        owns=[STATEFULSET, SERVICE],
        watches=[(POD, pods_to_tpujob_requests)],
        informers=informers,
        # Scrape-time fleet gauges (tpujob_jobs{phase}, slice-ready counts)
        # + the /debug/queue ledger hook/unhook with the controller
        # lifecycle, like the notebook fleet collector.
        on_start=on_start,
        on_stop=on_stop,
        resync_period=300.0,
        shards=shards,
    )

    def _kick(_etype, obj):
        # Capacity-change fan-out: any job delta on the GLOBAL feed wakes
        # the keys that can act on the new state — the head waiters
        # (admission), the current preemption targets (yield), and shrunk
        # gangs (grow-back) — filtered to this replica's owned shards.
        # The Queued-job poll (Result.requeue_after) is the guarantee;
        # this is the latency path.
        for ns, name in queue.kick_requests():
            req = Request(ns, name)
            if ctrl._owns(req):
                ctrl.queue.add(req)

    queue_informers[TPUJOB].add_handler(_kick)
    return ctrl
