"""TPUJob reconciler: TPUJob CR → gang of per-slice StatefulSets + Service.

The platform's first *training* workload (ROADMAP item 4 — the PR that
welds the repo's two halves together): the notebook reconciler's slice
conventions applied to batch jobs, plus the gang/restart semantics a
multi-slice ``jax.distributed`` job actually needs:

* **Gang creation** — one multi-host worker StatefulSet per ICI slice
  (``replicas = hosts(topology)``, pod ordinal == TPU worker id, Parallel
  pod management), every pod requesting ``google.com/tpu`` chips with the
  accelerator/topology node selectors, all behind ONE headless coordinator
  Service (``<name>-workers``, publishNotReadyAddresses) so worker DNS
  resolves during the rendezvous.
* **The env contract** — TPU_* per-slice bootstrap plus the MEGASCALE_*
  cross-slice identity, built from ``parallel/envspec.py`` — the SAME
  constants ``parallel/dist.py`` discovers with, so controller and trainer
  cannot drift.  ``spec.checkpointDir`` rides along as KFT_CHECKPOINT_DIR
  (the ``train/run.py`` --checkpoint-dir default).
* **All-or-nothing restarts** — any worker pod failing tears down the
  WHOLE generation (every slice's StatefulSet and pods) and recreates it
  under a bumped generation label; a restarted gang resumes from
  ``CheckpointManager.latest_step()`` because the checkpoint dir is stable
  across generations.  ``spec.backoffLimit`` bounds the gang restarts,
  ``restartPolicy: Never`` disables them.
* **Status aggregation** — Pending → Running → Succeeded/Failed/Restarting
  with per-slice ready counts and the restart counter, computed from pod
  phases read through the shard-filterable informer caches.

Terminal phases are sticky, and a finished gang's StatefulSets are deleted
so the chips free immediately (pods are left for log retrieval, like a
completed Job's).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from kubeflow_tpu.parallel import envspec
from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import tpujob as jobapi
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    POD,
    SERVICE,
    STATEFULSET,
    TPUJOB,
    Resource,
    deep_get,
    meta,
    name_of,
    pod_ready,
    set_owner,
    thaw,
)
from kubeflow_tpu.platform.runtime import EventRecorder, Reconciler, Request, Result
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.runtime.apply import patch_status_diff
from kubeflow_tpu.platform.runtime.flight import shared_pool
from kubeflow_tpu.platform.tpu import SliceSpec

GENERATION_ANNOTATION = "tpujobs.kubeflow.org/generation"


class _SliceNameConflict(Exception):
    """A slice StatefulSet name is already owned by a different workload."""


class TPUJobReconciler(Reconciler):
    def __init__(self, client, *, cluster_domain: Optional[str] = None,
                 informers: Optional[dict] = None):
        self.client = client
        # GVK -> Informer (make_controller wires them): pod/STS reads come
        # from the indexed caches — shard-filtered under sharded HA, so a
        # replica aggregates status only for gangs it owns.  Absent (bare
        # unit-test construction), reads fall back to client lists.
        self.informers: dict = informers or {}
        self.recorder = EventRecorder(client, "tpujob-controller")
        self.flights = shared_pool()
        self.cluster_domain = cluster_domain or config.env(
            "CLUSTER_DOMAIN", "cluster.local")

    # -- cache-backed reads ---------------------------------------------------

    def _cached_get(self, gvk, name: str, ns: str) -> Optional[Resource]:
        from kubeflow_tpu.platform.runtime.informer import cache_or_client_get

        return cache_or_client_get(self.informers.get(gvk), self.client,
                                   gvk, name, ns)

    def _pods_of(self, ns: str, name: str) -> List[Resource]:
        inf = self.informers.get(POD)
        if inf is not None:
            return inf.index_list("tpujob", f"{ns}/{name}")
        return self.client.list(
            POD, ns, label_selector={jobapi.LABEL_TPUJOB_NAME: name})

    def _stses_of(self, ns: str, name: str) -> List[Resource]:
        inf = self.informers.get(STATEFULSET)
        if inf is not None:
            return inf.index_list("tpujob", f"{ns}/{name}")
        return self.client.list(
            STATEFULSET, ns,
            label_selector={jobapi.LABEL_TPUJOB_NAME: name})

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            job = self.client.get(TPUJOB, req.name, req.namespace)
        except errors.NotFound:
            # ownerReference GC tears the gang down with the CR.
            return None

        try:
            jobapi.validate(job)
        except jobapi.ValidationError as e:
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "InvalidSpec", "message": str(e),
            }]}
            if job.get("status") != status:
                self.recorder.event(job, "Warning", "InvalidTPUJob", str(e))
                patch_status_diff(self.client, TPUJOB, job, status)
            return None

        if jobapi.phase_of(job) in jobapi.TERMINAL_PHASES:
            # Terminal is sticky; a new run is a new CR.  But finish any
            # chip-freeing teardown a transient fault interrupted after
            # the terminal status landed — otherwise the StatefulSets
            # would hold their TPU hosts forever.
            ns, name = meta(job)["namespace"], name_of(job)
            if self._stses_of(ns, name):
                self._teardown_gang(ns, name, delete_pods=False)
            return None

        spec = jobapi.tpu_slice(job)
        ns, name = meta(job)["namespace"], name_of(job)
        generation = jobapi.restarts_of(job)

        # Conflict-check every slice name BEFORE writing anything: a
        # partial gang would hold TPU hosts forever at the barrier.
        try:
            for s in range(spec.num_slices):
                self._check_sts_ownership(ns, name,
                                          self.slice_sts_name(name, s))
        except _SliceNameConflict as e:
            self.recorder.event(job, "Warning", "SliceNameConflict", str(e))
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "SliceNameConflict", "message": str(e),
            }]}
            if job.get("status") != status:
                patch_status_diff(self.client, TPUJOB, job, status)
            return None

        pods = self._pods_of(ns, name)
        current, stale = self._split_by_generation(pods, generation)
        # Stragglers of a torn-down generation: GC opportunistically so
        # they never pollute the new gang's aggregation.  Worker names are
        # REUSED across generations (STS ordinals), so a lagging informer
        # cache can present a just-recreated current-generation pod under
        # a stale object — re-check generation on a fresh GET before the
        # delete, or the GC kills a live worker of the new gang.
        for pod in stale:
            pod_name = name_of(pod)
            try:
                live = self.client.get(POD, pod_name, ns)
            except errors.NotFound:
                continue
            except errors.ApiError:
                continue  # retried on the requeue this reconcile gets
            live_gen = deep_get(live, "metadata", "labels",
                                jobapi.LABEL_GENERATION)
            if live_gen == str(generation):
                continue  # cache lag: the name already belongs to this gang
            try:
                self.client.delete(POD, pod_name, ns)
            except errors.ApiError:
                pass

        failed = [p for p in current
                  if deep_get(p, "status", "phase") == "Failed"]
        if failed:
            return self._handle_gang_failure(job, spec, generation, failed)

        self._reconcile_statefulsets(job, spec, generation)
        self._reconcile_headless_service(job)
        self._update_status(job, spec, generation, current)
        return None

    # -- gang restart ---------------------------------------------------------

    def _handle_gang_failure(self, job: Resource, spec: SliceSpec,
                             generation: int,
                             failed: List[Resource]) -> Optional[Result]:
        """All-or-nothing: one failed worker condemns the whole generation.
        Either recreate the gang under generation+1 (resume comes free:
        same checkpoint dir, ``latest_step()`` in the trainer) or, with the
        backoff exhausted / restartPolicy Never, go terminally Failed."""
        ns, name = meta(job)["namespace"], name_of(job)
        who = ", ".join(sorted(name_of(p) for p in failed))
        exhausted = (jobapi.restart_policy(job) == "Never"
                     or generation >= jobapi.backoff_limit(job))
        if exhausted:
            self._teardown_gang(ns, name, delete_pods=False)
            self.recorder.event(
                job, "Warning", "GangFailed",
                f"worker pod(s) {who} failed; restartPolicy="
                f"{jobapi.restart_policy(job)} backoffLimit="
                f"{jobapi.backoff_limit(job)} exhausted after "
                f"{generation} restart(s)")
            status = {
                "phase": jobapi.PHASE_FAILED,
                "restarts": generation,
                "slices": self._slice_counts_named(name, spec, {}),
                "conditions": [{
                    "type": "Failed", "status": "True",
                    "reason": "BackoffLimitExceeded",
                    "message": f"worker pod(s) {who} failed",
                }],
            }
            patch_status_diff(self.client, TPUJOB, job, status)
            return None
        self.recorder.event(
            job, "Warning", "GangRestart",
            f"worker pod(s) {who} failed; tearing down all "
            f"{spec.num_slices} slice(s) and restarting the gang "
            f"(generation {generation + 1})")
        status = {
            "phase": jobapi.PHASE_RESTARTING,
            "restarts": generation + 1,
            "slices": self._slice_counts_named(name, spec, {}),
        }
        # Persist the bumped counter BEFORE tearing anything down: the
        # teardown deletes the Failed pods (the evidence), so a crash or
        # transient status-write fault after it would replay this restart
        # at the SAME generation — an unaccounted restart that lets a
        # crashlooping job ride past backoffLimit forever.  With restarts
        # committed first, a retry resumes through the normal path (old-
        # generation pods/STSes read as stale and are GC'd/recreated).
        patch_status_diff(self.client, TPUJOB, job, status)
        metrics.tpujob_restarts_total.inc()
        self._teardown_gang(ns, name, delete_pods=True)
        # The deletion events re-enqueue this key; the next reconcile
        # creates the generation+1 StatefulSets against a clean slate.
        return None

    def _teardown_gang(self, ns: str, name: str, *,
                       delete_pods: bool) -> None:
        """Delete every slice StatefulSet (and, on a restart, every worker
        pod so the new generation starts clean; a terminally-Failed job
        keeps its pods for post-mortem logs, like a finished Job's)."""
        for sts in self._stses_of(ns, name):
            try:
                # Orphan on the keep-pods path: the default Background
                # propagation would cascade to the STS-owned worker pods
                # on a real cluster, silently breaking the kept-for-logs
                # contract (a restart deletes the pods itself below).
                self.client.delete(
                    STATEFULSET, name_of(sts), ns,
                    propagation="Background" if delete_pods else "Orphan")
            except errors.NotFound:
                pass
        if delete_pods:
            for pod in self._pods_of(ns, name):
                try:
                    self.client.delete(POD, name_of(pod), ns)
                except errors.ApiError:
                    pass

    # -- statefulsets ---------------------------------------------------------

    @staticmethod
    def slice_sts_name(name: str, slice_idx: int) -> str:
        """Slice 0 keeps the bare job name — worker ``<name>-0`` is the
        MEGASCALE coordinator, stable across generations — and later
        slices get ``<name>-s<i>``, the notebook reconciler's multislice
        layout (GKE's one-workload-per-slice shape)."""
        return name if slice_idx == 0 else f"{name}-s{slice_idx}"

    def generate_statefulset(self, job: Resource, slice_idx: int = 0,
                             generation: int = 0) -> Resource:
        ns, name = meta(job)["namespace"], name_of(job)
        spec = jobapi.tpu_slice(job)
        sts_name = self.slice_sts_name(name, slice_idx)

        pod_spec = thaw(
            deep_get(job, "spec", "template", "spec", default={}))
        containers = pod_spec.get("containers") or [{}]
        main = containers[0]
        main.setdefault("name", "worker")
        self._inject_tpu(pod_spec, main, ns, name, spec, slice_idx)
        ckpt = jobapi.checkpoint_dir(job)
        if ckpt:
            env = main.setdefault("env", [])
            if not any(e.get("name") == envspec.ENV_KFT_CHECKPOINT_DIR
                       for e in env):
                env.append({"name": envspec.ENV_KFT_CHECKPOINT_DIR,
                            "value": ckpt})

        labels = {
            "statefulset": sts_name,
            jobapi.LABEL_TPUJOB_NAME: name,
            jobapi.LABEL_TPUJOB_WORKER: "true",
            jobapi.LABEL_GENERATION: str(generation),
        }
        sts = {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sts_name,
                "namespace": ns,
                "labels": dict(labels),
                "annotations": {GENERATION_ANNOTATION: str(generation)},
            },
            "spec": {
                "replicas": spec.num_hosts,
                "serviceName": f"{name}-workers",
                "podManagementPolicy": "Parallel",  # the whole gang at once
                "selector": {"matchLabels": {"statefulset": sts_name}},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": pod_spec,
                },
            },
        }
        set_owner(sts, job)
        return sts

    def _inject_tpu(self, pod_spec: dict, container: dict, ns: str,
                    name: str, spec: SliceSpec, slice_idx: int) -> None:
        resources = container.setdefault("resources", {})
        resources.setdefault("limits", {}).update(spec.pod_resources())
        resources.setdefault("requests", {}).update(spec.pod_resources())
        pod_spec.setdefault("nodeSelector", {}).update(spec.node_selectors())
        sts_name = self.slice_sts_name(name, slice_idx)
        hostnames = ",".join(
            f"{sts_name}-{i}.{name}-workers.{ns}.svc.{self.cluster_domain}"
            for i in range(spec.num_hosts)
        )
        env = container.setdefault("env", [])
        have = {e.get("name") for e in env}
        # Per-slice libtpu bootstrap + cross-slice MEGASCALE identity, all
        # built by the shared envspec helpers.  Unlike the notebook path,
        # MEGASCALE_* is injected even at num_slices=1: a TPUJob's trainer
        # always runs dist.initialize_from_env, and the uniform contract
        # keeps the round-trip test one shape.
        injected = envspec.tpu_bootstrap_env(
            topology=spec.topology,
            accelerator=spec.accelerator.name,
            chips=spec.chips,
            chips_per_host=spec.chips_per_pod,
            num_hosts=spec.num_hosts,
            hostnames=hostnames,
        ) + envspec.megascale_env(
            slice_idx, spec.num_slices,
            f"{name}-0.{name}-workers.{ns}.svc.{self.cluster_domain}")
        env.extend(e for e in injected if e["name"] not in have)

    def _check_sts_ownership(self, ns: str, job_name: str,
                             sts_name: str) -> None:
        current = self._cached_get(STATEFULSET, sts_name, ns)
        if current is None:
            return
        owner = deep_get(current, "metadata", "labels",
                         jobapi.LABEL_TPUJOB_NAME)
        if owner != job_name:
            raise _SliceNameConflict(
                f"StatefulSet {ns}/{sts_name} belongs to "
                f"{'TPUJob ' + owner if owner else 'another workload'}, "
                f"not TPUJob {job_name}; rename one of them")

    def _reconcile_statefulsets(self, job: Resource, spec: SliceSpec,
                                generation: int) -> None:
        """Gang-create: every missing slice StatefulSet of the CURRENT
        generation, concurrently (independent names, one owner).  A
        leftover from an older generation (a teardown delete that lost a
        race) is deleted and recreated."""
        ns, name = meta(job)["namespace"], name_of(job)
        created = self.flights.run([
            (lambda s=s: self._reconcile_one_statefulset(
                job, s, generation))
            for s in range(spec.num_slices)
        ])
        if any(created):
            self.recorder.event(
                job, "Normal", "GangCreated",
                f"created {spec.num_slices} slice StatefulSet(s) x "
                f"{spec.num_hosts} worker(s) (generation {generation})")

    def _reconcile_one_statefulset(self, job: Resource, slice_idx: int,
                                   generation: int) -> bool:
        """Returns True when this pass created the slice's StatefulSet."""
        desired = self.generate_statefulset(job, slice_idx, generation)
        ns, name = meta(desired)["namespace"], name_of(desired)
        current = self._cached_get(STATEFULSET, name, ns)
        if current is not None:
            live_gen = deep_get(current, "metadata", "annotations",
                                GENERATION_ANNOTATION)
            if live_gen == str(generation):
                return False
            # Older generation still standing (teardown raced a transient
            # delete failure): clear it now, recreate below.
            try:
                self.client.delete(STATEFULSET, name, ns)
            except errors.NotFound:
                pass
        try:
            self.client.create(desired)
            return True
        except errors.AlreadyExists:
            # Cache lag on a just-created STS — or an injected/raced 409
            # whose create never landed.  Verify with a fresh GET: present
            # means someone (us, a moment ago) created it; absent means
            # the create really failed, so raise for a backoff requeue
            # instead of silently parking the slice until resync.
            try:
                self.client.get(STATEFULSET, name, ns)
            except errors.NotFound:
                raise
            return False

    # -- coordinator service --------------------------------------------------

    def generate_headless_service(self, job: Resource) -> Resource:
        ns, name = meta(job)["namespace"], name_of(job)
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{name}-workers", "namespace": ns,
                         "labels": {jobapi.LABEL_TPUJOB_NAME: name}},
            "spec": {
                "clusterIP": "None",
                # Worker DNS must resolve BEFORE readiness: the
                # jax.distributed rendezvous happens during bring-up.
                "publishNotReadyAddresses": True,
                # One governing service spans every slice's StatefulSet —
                # cross-slice (DCN) coordinator DNS resolves through it.
                "selector": {jobapi.LABEL_TPUJOB_NAME: name},
                "ports": [{"name": "coordinator",
                           "port": envspec.DEFAULT_COORDINATOR_PORT,
                           "protocol": "TCP"}],
            },
        }
        set_owner(svc, job)
        return svc

    def _reconcile_headless_service(self, job: Resource) -> None:
        desired = self.generate_headless_service(job)
        ns, name = meta(desired)["namespace"], name_of(desired)
        if self._cached_get(SERVICE, name, ns) is not None:
            return  # spec is generation-invariant; nothing to update
        try:
            self.client.create(desired)
        except errors.AlreadyExists:
            pass

    # -- status ---------------------------------------------------------------

    @staticmethod
    def _split_by_generation(pods: List[Resource], generation: int):
        current, stale = [], []
        for pod in pods:
            gen = deep_get(pod, "metadata", "labels",
                           jobapi.LABEL_GENERATION)
            (current if gen == str(generation) else stale).append(pod)
        return current, stale

    def _update_status(self, job: Resource, spec: SliceSpec,
                       generation: int, current: List[Resource]) -> None:
        ns, name = meta(job)["namespace"], name_of(job)
        expected = [
            f"{self.slice_sts_name(name, s)}-{i}"
            for s in range(spec.num_slices)
            for i in range(spec.num_hosts)
        ]
        by_name = {name_of(p): p for p in current}
        phases = {n: deep_get(by_name[n], "status", "phase")
                  for n in expected if n in by_name}
        succeeded = sum(1 for p in phases.values() if p == "Succeeded")
        ready = sum(1 for n in expected
                    if n in by_name and pod_ready(by_name[n]))

        if succeeded == len(expected):
            phase = jobapi.PHASE_SUCCEEDED
        elif ready + succeeded == len(expected):
            # Workers finish at slightly different times (the collective
            # tears down rank by rank): a pod that already exited 0 is no
            # longer Ready but must keep counting toward Running, or a
            # completing job would read as Pending/Restarting for its last
            # few seconds.
            phase = jobapi.PHASE_RUNNING
        elif generation > 0:
            phase = jobapi.PHASE_RESTARTING
        else:
            phase = jobapi.PHASE_PENDING

        status: dict = {
            "phase": phase,
            "restarts": generation,
            "slices": self._slice_counts_named(name, spec, by_name),
        }
        if job.get("status") != status:
            patch_status_diff(self.client, TPUJOB, job, status)
        if phase == jobapi.PHASE_SUCCEEDED:
            # Terminal phase committed; NOW free the chips (keep the
            # Succeeded pods for logs).  The reverse order let a transient
            # fault on the status write recreate the finished gang: with
            # the STSes already gone and the stored phase still Running,
            # the retry reached _reconcile_statefulsets first.  If THIS
            # teardown faults instead, the terminal-sticky branch in
            # reconcile() finishes the sweep.
            self._teardown_gang(ns, name, delete_pods=False)
            self.recorder.event(
                job, "Normal", "JobSucceeded",
                f"all {len(expected)} worker(s) across {spec.num_slices} "
                f"slice(s) succeeded after {generation} restart(s)")

    def _slice_counts_named(self, name: str, spec: SliceSpec,
                            by_name: Dict[str, Resource]) -> List[dict]:
        out = []
        for s in range(spec.num_slices):
            sts = self.slice_sts_name(name, s)
            ready = sum(
                1 for i in range(spec.num_hosts)
                if f"{sts}-{i}" in by_name
                and pod_ready(by_name[f"{sts}-{i}"]))
            out.append({"slice": s, "ready": ready,
                        "total": spec.num_hosts})
        return out


# -- watch mappers / indexers -------------------------------------------------


def pods_to_tpujob_requests(obj: Resource) -> List[Request]:
    """Watch mapper: pod events → owning TPUJob (by tpujob-name label)."""
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    job = labels.get(jobapi.LABEL_TPUJOB_NAME)
    if not job:
        return []
    return [Request(deep_get(obj, "metadata", "namespace", default=""), job)]


def _job_label_index(obj: Resource) -> List[str]:
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    job = labels.get(jobapi.LABEL_TPUJOB_NAME)
    ns = deep_get(obj, "metadata", "namespace", default="")
    return [f"{ns}/{job}"] if job else []


def make_controller(client, **kwargs):
    from kubeflow_tpu.platform.runtime import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer

    # Sharded HA: same contract as the other four controllers — the
    # coordinator shard-filters these informers (a worker pod is cached
    # iff its owning job's key is owned) and the FencedClient proves every
    # gang write against the key's shard lease.
    shards = kwargs.pop("shards", None)
    informers = {
        TPUJOB: Informer(client, TPUJOB),
        POD: Informer(client, POD, indexers={"tpujob": _job_label_index}),
        STATEFULSET: Informer(client, STATEFULSET,
                              indexers={"tpujob": _job_label_index}),
        SERVICE: Informer(client, SERVICE),
    }
    return Controller(
        "tpujob-controller",
        TPUJobReconciler(client, informers=informers, **kwargs),
        primary=TPUJOB,
        owns=[STATEFULSET, SERVICE],
        watches=[(POD, pods_to_tpujob_requests)],
        informers=informers,
        # Scrape-time fleet gauges (tpujob_jobs{phase}, slice-ready counts)
        # hook/unhook with the controller lifecycle, like the notebook
        # fleet collector.
        on_start=lambda: metrics.register_tpujob_collector(client),
        on_stop=lambda: metrics.register_tpujob_collector(None),
        resync_period=300.0,
        shards=shards,
    )
