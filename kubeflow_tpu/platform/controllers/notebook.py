"""Notebook reconciler: Notebook CR → StatefulSet + Services + VirtualService.

The TPU-first re-architecture of the reference control loop (reference
notebook_controller.go:89-225 / 361-565).  Structural differences, all
driven by multi-host TPU slices:

* ``replicas = num_hosts(topology)`` instead of the reference's hard-coded 1
  (notebook_controller.go:362) — one pod per TPU host, StatefulSet ordinal
  == TPU worker id.
* A headless service always exists for stable per-worker DNS
  (``<name>-<i>.<name>-workers.<ns>``), published before readiness so
  ``jax.distributed.initialize`` can rendezvous during bring-up.
* The user-facing Service targets **worker 0 only** (pod-name selector) —
  the Jupyter kernel and the culling probe live on the coordinator.
* TPU env (TPU_WORKER_ID via the pod-index label downward API,
  TPU_WORKER_HOSTNAMES, TPU_TOPOLOGY, TPU_ACCELERATOR_TYPE) and
  ``google.com/tpu`` chip limits + GKE topology node selectors are injected
  from ``spec.tpu`` — the path the reference routes through a GPU-vendor
  limits dict (form.py:226-250) is a scheduling concern here, not a form
  concern.
* Stop/start (``kubeflow-resource-stopped``) scales the whole slice to 0
  and back atomically — all workers, one replicas field.
"""
from __future__ import annotations

import copy
import hashlib
import json
import time
from typing import Dict, List, Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.apis import notebook as nbapi
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    EVENT,
    NOTEBOOK,
    POD,
    PODDISRUPTIONBUDGET,
    SERVICE,
    STATEFULSET,
    VIRTUALSERVICE,
    Resource,
    deep_get,
    meta,
    name_of,
    pod_ready,
    set_owner,
    thaw,
)
from kubeflow_tpu.platform.runtime import EventRecorder, Reconciler, Request, Result
from kubeflow_tpu.platform.runtime import metrics
from kubeflow_tpu.platform.runtime import apply
from kubeflow_tpu.platform.runtime.apply import merge_patch_for, patch_status_diff
from kubeflow_tpu.platform.runtime.flight import shared_pool
from kubeflow_tpu.platform.tpu import SliceSpec

HASH_ANNOTATION = "notebooks.kubeflow.org/generated-hash"


class _SliceNameConflict(Exception):
    """A slice StatefulSet name is already owned by a different notebook."""


def _content_hash(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()[:16]


class NotebookReconciler(Reconciler):
    def __init__(self, client, *, use_istio: Optional[bool] = None,
                 istio_gateway: Optional[str] = None,
                 cluster_domain: Optional[str] = None,
                 add_fsgroup: Optional[bool] = None,
                 mirror_min_interval: Optional[float] = None,
                 informers: Optional[dict] = None):
        self.client = client
        # GVK -> Informer for the high-churn secondary reads (pods, events).
        # When present (make_controller wires them), reconcile reads these
        # kinds from the indexed cache — O(matches) instead of a per-
        # reconcile apiserver LIST, which was quadratic across a fleet
        # (bench_scale.py).  Absent (unit tests constructing the reconciler
        # bare), reads fall back to client lists — same results, both paths
        # covered.  Freshness: the cache is updated before the controller's
        # informer-sourced mappers enqueue (runtime.Controller), so a
        # reconcile triggered by a pod/event delta always sees it.
        self.informers: dict = informers or {}
        self.recorder = EventRecorder(client, "notebook-controller")
        # Bounded shared fan-out for independent secondary writes: the
        # slice StatefulSets and the Service/headless-Service/PDB/
        # VirtualService quartet have no ordering dependency on each
        # other, so they fly concurrently (runtime/flight.py) while
        # status aggregation still waits on every result.
        self.flights = shared_pool()
        self.use_istio = (
            use_istio if use_istio is not None else config.env_bool("USE_ISTIO", True)
        )
        self.istio_gateway = istio_gateway or config.env(
            "ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"
        )
        self.cluster_domain = cluster_domain or config.env("CLUSTER_DOMAIN", "cluster.local")
        self.add_fsgroup = (
            add_fsgroup if add_fsgroup is not None else config.env_bool("ADD_FSGROUP", True)
        )
        # (ns, name) -> monotonic time of the last event-mirroring pass.
        self._mirror_last: Dict[tuple, float] = {}
        self.mirror_min_interval = (
            mirror_min_interval
            if mirror_min_interval is not None
            else self.MIRROR_MIN_INTERVAL_SECONDS
        )

    # -- cache-backed reads ---------------------------------------------------

    def _cached_get(self, gvk, name: str, ns: str) -> Optional[Resource]:
        """One object by key: zero-copy frozen cache view when the kind's
        informer is wired and synced, live GET otherwise.  Returns None for
        not-found on either path.  Writers must thaw() before mutating; a
        create against a cache-lagged None gets AlreadyExists and falls
        back to a fresh GET at the call site — never fight the cache."""
        from kubeflow_tpu.platform.runtime.informer import cache_or_client_get

        return cache_or_client_get(self.informers.get(gvk), self.client,
                                   gvk, name, ns)

    def _pods_of(self, ns: str, name: str) -> List[Resource]:
        """This notebook's worker pods: indexed cache read when informers
        are wired, label-selector LIST otherwise."""
        inf = self.informers.get(POD)
        if inf is not None:
            return inf.index_list("notebook", f"{ns}/{name}")
        return self.client.list(
            POD, ns, label_selector={nbapi.LABEL_NOTEBOOK_NAME: name}
        )

    def _stses_of(self, ns: str, name: str) -> List[Resource]:
        """This notebook's slice StatefulSets (for stale-slice GC):
        indexed cache read when wired, label-selector LIST otherwise.  GC
        from a cache is safe here: a just-created slice missing from a
        stale cache merely skips this pass (it is never deleted for being
        absent), and a lowered slice count re-triggers via the owned-STS
        delta — level-triggered reconcile converges."""
        inf = self.informers.get(STATEFULSET)
        if inf is not None:
            return inf.index_list("notebook", f"{ns}/{name}")
        return self.client.list(
            STATEFULSET, ns,
            label_selector={nbapi.LABEL_NOTEBOOK_NAME: name})

    def _events_involving(self, ns: str, kind: str, name: str) -> List[Resource]:
        """Events on one involved object: indexed cache read, or a field-
        selected LIST (involvedObject.* is apiserver-indexed for Events)."""
        inf = self.informers.get(EVENT)
        if inf is not None:
            return inf.index_list("involved", f"{ns}/{kind}/{name}")
        return self.client.list(
            EVENT, ns,
            field_selector={"involvedObject.kind": kind,
                            "involvedObject.name": name})

    def _pod_events_of_sts(self, ns: str, sts_name: str) -> List[Resource]:
        """Events on ANY worker pod ``<sts>-<ordinal>`` of one StatefulSet,
        including pods that no longer exist."""
        inf = self.informers.get(EVENT)
        if inf is not None:
            return inf.index_list("involved", f"{ns}/Pod-of/{sts_name}")
        out = []
        for ev in self.client.list(EVENT, ns):
            io = ev.get("involvedObject") or {}
            if io.get("kind") != "Pod":
                continue
            prefix, _, ordinal = (io.get("name") or "").rpartition("-")
            if prefix == sts_name and ordinal.isdigit():
                out.append(ev)
        return out

    def _get_event(self, name: str, ns: str) -> Resource:
        inf = self.informers.get(EVENT)
        if inf is not None:
            obj = inf.get(name, ns)
            if obj is None:
                raise errors.NotFound(f'events "{name}" not found in "{ns}"')
            return obj
        return self.client.get(EVENT, name, ns)

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            notebook = self.client.get(NOTEBOOK, req.name, req.namespace)
        except errors.NotFound:
            # ownerReference GC tears down children; the fleet gauges are
            # scrape-time collectors (metrics.NotebookFleetCollector), so a
            # deleted notebook's chips vanish at the next scrape.
            self._mirror_last.pop((req.namespace, req.name), None)
            # Unconditionally: a failed-over leader has no memory of the
            # key but the durable marker still exists — a leaked marker
            # would throttle a same-named successor's first mirror pass.
            try:
                self.client.delete(
                    EVENT, req.name + self.MIRROR_MARKER_SUFFIX,
                    req.namespace,
                )
            except errors.ApiError:
                pass
            return None

        # Invalid specs (bad TPU topology etc.) are terminal user errors:
        # surface them as a Warning event + status instead of crash-looping
        # the queue (a probe found exactly that failure mode).
        try:
            nbapi.validate(notebook)
        except nbapi.ValidationError as e:
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "InvalidSpec", "message": str(e),
            }]}
            if notebook.get("status") != status:
                self.recorder.event(notebook, "Warning", "InvalidNotebook", str(e))
                patch_status_diff(self.client, NOTEBOOK, notebook, status)
            return None

        stses = self._reconcile_statefulsets(notebook)
        if stses is None:
            # Parked on a slice-name conflict (terminal until renamed).
            return None
        # The four service-layer secondaries are independent of each other
        # (and of the already-written StatefulSets): fly them concurrently.
        # run() waits for ALL and re-raises the first failure AFTER every
        # sibling settled, so one failed write never hides the others and
        # the backoff requeue retries the lot (level-triggered).
        secondary_writes = [
            lambda: self._reconcile_service(notebook),
            lambda: self._reconcile_headless_service(notebook),
            lambda: self._reconcile_pdb(notebook),
        ]
        if self.use_istio:
            secondary_writes.append(
                lambda: self._reconcile_virtual_service(notebook))
        self.flights.run(secondary_writes)
        self._update_status(notebook, stses)
        self._mirror_events(notebook)
        return None

    # -- statefulset ---------------------------------------------------------

    @staticmethod
    def slice_sts_name(name: str, slice_idx: int) -> str:
        """Slice 0 keeps the bare notebook name (so worker 0 is ``<name>-0``
        — UI routing, culling, and status never change); later slices get
        ``<name>-s<i>`` StatefulSets, mirroring GKE multislice's
        one-Job-per-slice layout."""
        return name if slice_idx == 0 else f"{name}-s{slice_idx}"

    def generate_statefulset(
        self, notebook: Resource, slice_idx: int = 0
    ) -> Resource:
        ns = meta(notebook)["namespace"]
        name = name_of(notebook)
        tpu = nbapi.tpu_slice(notebook)
        replicas = 0 if nbapi.is_stopped(notebook) else (tpu.num_hosts if tpu else 1)
        sts_name = self.slice_sts_name(name, slice_idx)

        # thaw(): plain mutable deep copy whether the notebook came from a
        # fresh GET or a frozen cache view (copy_resource under the hood —
        # measurably cheaper than copy.deepcopy on this per-reconcile path).
        pod_spec = thaw(
            deep_get(notebook, "spec", "template", "spec", default={})
        )
        containers = pod_spec.get("containers") or [{}]
        main = containers[0]
        main.setdefault("name", name)

        self._inject_prefix_env(main, ns, name)
        if tpu:
            self._inject_tpu(pod_spec, main, ns, name, tpu, slice_idx)
        if self.add_fsgroup:
            pod_spec.setdefault("securityContext", {}).setdefault("fsGroup", 100)

        labels = {
            # Per-STS selector label (must be unique per slice so each
            # StatefulSet selects only its own pods)...
            "statefulset": sts_name,
            # ...and the cross-slice notebook label the headless service,
            # PDB, and status aggregation select on.
            nbapi.LABEL_NOTEBOOK_NAME: name,
        }
        sts = {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": sts_name,
                "namespace": ns,
                "labels": dict(labels),
            },
            "spec": {
                "replicas": replicas,
                "serviceName": f"{name}-workers",
                "podManagementPolicy": "Parallel",  # all TPU workers at once
                "selector": {"matchLabels": {"statefulset": sts_name}},
                "template": {
                    "metadata": {"labels": dict(labels)},
                    "spec": pod_spec,
                },
            },
        }
        set_owner(sts, notebook)
        return sts

    def _inject_prefix_env(self, container: dict, ns: str, name: str) -> None:
        env = container.setdefault("env", [])
        if not any(e.get("name") == "NB_PREFIX" for e in env):
            env.append({"name": "NB_PREFIX", "value": nbapi.nb_prefix(ns, name)})

    def _inject_tpu(self, pod_spec: dict, container: dict, ns: str, name: str,
                    tpu: SliceSpec, slice_idx: int = 0) -> None:
        # Chip limits on the main container (per pod == per host).
        resources = container.setdefault("resources", {})
        limits = resources.setdefault("limits", {})
        limits.update(tpu.pod_resources())
        requests = resources.setdefault("requests", {})
        requests.update(tpu.pod_resources())
        # Topology-aware placement.
        selectors = pod_spec.setdefault("nodeSelector", {})
        selectors.update(tpu.node_selectors())
        # Worker env: ordinal from the pod-index label (statefulset pods get
        # apps.kubernetes.io/pod-index), hostnames from the headless service.
        # TPU_WORKER_ID/TPU_WORKER_HOSTNAMES are libtpu's *per-slice* ICI
        # bootstrap contract (same variables GKE's TPU webhook injects), so
        # each slice's StatefulSet lists only its own hosts and pod ordinals
        # restart from 0 per slice; the MEGASCALE_* variables carry the
        # cross-slice (DCN) identity.
        sts_name = self.slice_sts_name(name, slice_idx)
        hostnames = ",".join(
            f"{sts_name}-{i}.{name}-workers.{ns}.svc.{self.cluster_domain}"
            for i in range(tpu.num_hosts)
        )
        env = container.setdefault("env", [])
        have = {e.get("name") for e in env}
        # The whole block (names AND value formats) comes from
        # parallel/envspec.py — what parallel/dist.py discovers with,
        # shared with the TPUJob controller, so injection and discovery
        # cannot drift between workloads.
        from kubeflow_tpu.parallel import envspec

        injected = envspec.tpu_bootstrap_env(
            topology=tpu.topology,
            accelerator=tpu.accelerator.name,
            chips=tpu.chips,
            chips_per_host=tpu.chips_per_pod,
            num_hosts=tpu.num_hosts,
            hostnames=hostnames,
        )
        if tpu.multi_slice:
            # DCN mesh contract (GKE multislice parity): every worker learns
            # its slice, the slice count, and the coordinator — worker 0 of
            # slice 0 (pod <name>-0, stable across slice STSes).
            injected += envspec.megascale_env(
                slice_idx, tpu.num_slices,
                f"{name}-0.{name}-workers.{ns}.svc.{self.cluster_domain}")
        env.extend(e for e in injected if e["name"] not in have)

    def _reconcile_statefulsets(
        self, notebook: Resource
    ) -> Optional[List[Resource]]:
        """One StatefulSet per slice; stale slice STSes (slices lowered) are
        deleted so their pods don't linger outside the new mesh.  Returns
        None when parked on a slice-name conflict."""
        tpu = nbapi.tpu_slice(notebook)
        n_slices = tpu.num_slices if tpu else 1
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        # Conflict-check every slice name BEFORE writing anything: a partial
        # deployment (slice 0 created, slice 1 conflicted) would hold TPU
        # hosts forever at the jax.distributed barrier.
        try:
            for s in range(n_slices):
                self._check_sts_ownership(ns, name, self.slice_sts_name(name, s))
        except _SliceNameConflict as e:
            # A sibling notebook legally named `<name>-s<i>` owns that
            # StatefulSet; fighting over it would flap both workloads.
            # Park this notebook instead — terminal until renamed.
            self.recorder.event(notebook, "Warning", "SliceNameConflict", str(e))
            status = {"conditions": [{
                "type": "Degraded", "status": "True",
                "reason": "SliceNameConflict", "message": str(e),
            }]}
            if notebook.get("status") != status:
                patch_status_diff(self.client, NOTEBOOK, notebook, status)
            return None
        # Every slice StatefulSet is independent (distinct names, one
        # owner): write them concurrently through the bounded pool — a
        # 4-slice notebook pays one round trip of latency, not four.
        out = self.flights.run([
            (lambda s=s: self._reconcile_one_statefulset(notebook, s))
            for s in range(n_slices)
        ])
        expected = {self.slice_sts_name(name, s) for s in range(n_slices)}
        # A transient list failure must raise (requeue with backoff) — a
        # silent skip would leave a scaled-down slice's pods holding TPUs
        # until the next unrelated event.
        owned = self._stses_of(ns, name)
        for sts in owned:
            if name_of(sts) not in expected:
                try:
                    self.client.delete(STATEFULSET, name_of(sts), ns)
                except errors.NotFound:
                    pass
        return out

    def _check_sts_ownership(self, ns: str, notebook_name: str,
                             sts_name: str) -> None:
        current = self._cached_get(STATEFULSET, sts_name, ns)
        if current is None:
            return
        owner = deep_get(current, "metadata", "labels", nbapi.LABEL_NOTEBOOK_NAME)
        if owner != notebook_name:
            raise _SliceNameConflict(
                f"StatefulSet {ns}/{sts_name} belongs to notebook "
                f"{owner or '<unlabelled>'}, not {notebook_name}; rename one "
                f"of the notebooks to resolve the multislice name collision"
            )

    def _reconcile_one_statefulset(
        self, notebook: Resource, slice_idx: int
    ) -> Resource:
        desired = self.generate_statefulset(notebook, slice_idx)
        ns, name = meta(desired)["namespace"], name_of(desired)
        # Semantic ownership via content hash: the live object accretes
        # server defaults (imagePullPolicy, dnsPolicy, ...) that make
        # subtree equality always-false against a real API server; a hash
        # annotation of the *generated* template compares desired-vs-desired
        # (the Deployment pod-template-hash idiom).
        desired_hash = _content_hash(desired["spec"]["template"])
        meta(desired).setdefault("annotations", {})[HASH_ANNOTATION] = desired_hash
        current = self._cached_get(STATEFULSET, name, ns)
        if current is None:
            try:
                created = apply.create(self.client, desired)
            except errors.AlreadyExists:
                # Cache lag: a just-created STS hasn't landed in the
                # informer yet.  Re-read fresh and fall through to the
                # compare-and-update path instead of erroring the key —
                # unless the fresh object belongs to a DIFFERENT notebook
                # (a conflict the lagging ownership pre-check missed);
                # never update a sibling's StatefulSet.
                try:
                    current = self.client.get(STATEFULSET, name, ns)
                except errors.NotFound:
                    # Created-then-deleted race: this pass failed its
                    # create (count it); the backoff requeue recreates.
                    metrics.notebook_create_failed_total.inc()
                    raise
                owner = deep_get(current, "metadata", "labels",
                                 nbapi.LABEL_NOTEBOOK_NAME)
                if owner != name_of(notebook):
                    # A genuine create failure (the name belongs to a
                    # sibling): count it — the bare raise skips the
                    # except-ApiError branch below, which used to do so.
                    metrics.notebook_create_failed_total.inc()
                    raise
            except errors.ApiError:
                metrics.notebook_create_failed_total.inc()
                raise
            else:
                metrics.notebook_create_total.inc()
                self.recorder.event(
                    notebook, "Normal", "CreatedStatefulSet",
                    f"Created StatefulSet {name} "
                    f"(replicas={deep_get(desired, 'spec', 'replicas')})",
                )
                return created
        changed_replicas = (deep_get(current, "spec", "replicas")
                            != deep_get(desired, "spec", "replicas"))
        current_hash = deep_get(current, "metadata", "annotations", HASH_ANNOTATION)
        if changed_replicas or current_hash != desired_hash:
            # Diff-and-patch the owned fields only (JSON merge patch): the
            # frozen cache view is read directly — no thaw, no full-object
            # PUT, and no resourceVersion precondition, so a stale cache
            # can no longer turn into a 409 on this path at all.
            spec_patch: dict = {}
            if changed_replicas:
                spec_patch["replicas"] = deep_get(desired, "spec", "replicas")
            if current_hash != desired_hash:
                template_diff = merge_patch_for(
                    deep_get(current, "spec", "template", default={}),
                    desired["spec"]["template"])
                if template_diff is not None:
                    spec_patch["template"] = template_diff
            patch: dict = {
                "metadata": {"annotations": {HASH_ANNOTATION: desired_hash}}}
            if spec_patch:
                patch["spec"] = spec_patch
            return self.client.patch(STATEFULSET, name, patch, ns)
        return current

    # -- services ------------------------------------------------------------

    def generate_service(self, notebook: Resource) -> Resource:
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        tpu = nbapi.tpu_slice(notebook)
        port = nbapi.notebook_port(notebook)
        # Multi-host: route the UI to worker 0, where the kernel lives.
        selector = (
            {"statefulset.kubernetes.io/pod-name": f"{name}-0"}
            if tpu and tpu.multi_host
            else {"statefulset": name}
        )
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "type": "ClusterIP",
                "selector": selector,
                "ports": [{
                    "name": nbapi.service_port_name(name),
                    "port": 80,
                    "targetPort": port,
                    "protocol": "TCP",
                }],
            },
        }
        set_owner(svc, notebook)
        return svc

    def generate_headless_service(self, notebook: Resource) -> Resource:
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        port = nbapi.notebook_port(notebook)
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{name}-workers", "namespace": ns},
            "spec": {
                "clusterIP": "None",
                # Resolve worker DNS before readiness: jax.distributed
                # rendezvous happens while pods are still NotReady.
                "publishNotReadyAddresses": True,
                # Notebook-name label spans every slice's StatefulSet, so
                # cross-slice (DCN) worker DNS resolves through this one
                # governing service.
                "selector": {nbapi.LABEL_NOTEBOOK_NAME: name},
                "ports": [{"name": "coordinator", "port": port, "protocol": "TCP"}],
            },
        }
        set_owner(svc, notebook)
        return svc

    def _reconcile_service(self, notebook: Resource) -> Resource:
        return self._create_or_update_service(self.generate_service(notebook))

    def _reconcile_headless_service(self, notebook: Resource) -> Resource:
        return self._create_or_update_service(self.generate_headless_service(notebook))

    def _create_or_update_service(self, desired: Resource) -> Resource:
        ns, name = meta(desired)["namespace"], name_of(desired)
        desired_hash = _content_hash(desired["spec"])
        meta(desired).setdefault("annotations", {})[HASH_ANNOTATION] = desired_hash
        current = self._cached_get(SERVICE, name, ns)
        if current is None:
            try:
                return apply.create(self.client, desired)
            except errors.AlreadyExists:
                # Cache lag — re-read fresh and reconcile against it.
                current = self.client.get(SERVICE, name, ns)
        if deep_get(current, "metadata", "annotations", HASH_ANNOTATION) == desired_hash:
            return current
        # Patch only controller-owned fields; keep server-populated ones
        # (clusterIP is immutable — reference CopyServiceFields preserves
        # it, here by folding the live value into the desired spec before
        # the diff so the patch never touches it).
        want = copy.deepcopy(desired["spec"])
        if "clusterIP" in current.get("spec", {}) and want.get("clusterIP") != "None":
            want["clusterIP"] = current["spec"]["clusterIP"]
        patch: dict = {
            "metadata": {"annotations": {HASH_ANNOTATION: desired_hash}}}
        spec_diff = merge_patch_for(current.get("spec"), want)
        if spec_diff is not None:
            patch["spec"] = spec_diff
        return self.client.patch(SERVICE, name, patch, ns)

    # -- pod disruption budget ----------------------------------------------

    def generate_pdb(self, notebook: Resource) -> Optional[Resource]:
        """Multi-host slices are all-or-nothing: evicting one worker kills
        the whole slice's `jax.distributed` job, so voluntary disruptions
        must never take a single worker.  minAvailable = num_hosts blocks
        them all; single-host notebooks need no PDB (no reference analogue
        — the reference never schedules multi-pod workloads)."""
        tpu = nbapi.tpu_slice(notebook)
        if not tpu or not tpu.multi_host or nbapi.is_stopped(notebook):
            return None
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        pdb = {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {"name": f"{name}-slice", "namespace": ns},
            "spec": {
                "minAvailable": tpu.total_hosts,
                "selector": {"matchLabels": {nbapi.LABEL_NOTEBOOK_NAME: name}},
            },
        }
        set_owner(pdb, notebook)
        return pdb

    def _reconcile_pdb(self, notebook: Resource) -> None:
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        desired = self.generate_pdb(notebook)
        pdb_name = f"{name}-slice"
        current = self._cached_get(PODDISRUPTIONBUDGET, pdb_name, ns)
        if desired is None:
            # Single-host, stopped, or spec changed away from multi-host: a
            # leftover PDB would block node drains forever.  Read-then-
            # delete keeps the common single-host reconcile off the API
            # server's write path entirely.
            if current is not None:
                try:
                    self.client.delete(PODDISRUPTIONBUDGET, pdb_name, ns)
                except errors.NotFound:
                    pass
            return
        if current is None:
            try:
                apply.create(self.client, desired)
            except errors.AlreadyExists:
                current = self.client.get(PODDISRUPTIONBUDGET, pdb_name, ns)
            else:
                return
        spec_diff = merge_patch_for(current.get("spec"), desired.get("spec"))
        if spec_diff is not None:
            self.client.patch(PODDISRUPTIONBUDGET, pdb_name,
                              {"spec": spec_diff}, ns)

    # -- istio ---------------------------------------------------------------

    def generate_virtual_service(self, notebook: Resource) -> Resource:
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        prefix = nbapi.nb_prefix(ns, name) + "/"
        annotations = deep_get(notebook, "metadata", "annotations", default={}) or {}
        rewrite = annotations.get(nbapi.ANNOTATION_REWRITE_URI) or "/"
        route: dict = {
            "destination": {
                "host": f"{name}.{ns}.svc.{self.cluster_domain}",
                "port": {"number": 80},
            }
        }
        headers_set = annotations.get(nbapi.ANNOTATION_HEADERS_REQUEST_SET)
        http_route: dict = {
            "match": [{"uri": {"prefix": prefix}}],
            "rewrite": {"uri": rewrite},
            "route": [route],
            "timeout": "300s",
        }
        if headers_set:
            import json

            try:
                http_route["headers"] = {"request": {"set": json.loads(headers_set)}}
            except ValueError:
                pass
        vs = {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"notebook-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": ["*"],
                "gateways": [self.istio_gateway],
                "http": [http_route],
            },
        }
        set_owner(vs, notebook)
        return vs

    def _reconcile_virtual_service(self, notebook: Resource) -> Resource:
        desired = self.generate_virtual_service(notebook)
        ns, name = meta(desired)["namespace"], name_of(desired)
        current = self._cached_get(VIRTUALSERVICE, name, ns)
        if current is None:
            try:
                return apply.create(self.client, desired)
            except errors.AlreadyExists:
                current = self.client.get(VIRTUALSERVICE, name, ns)
        spec_diff = merge_patch_for(current.get("spec"), desired.get("spec"))
        if spec_diff is not None:
            return self.client.patch(VIRTUALSERVICE, name,
                                     {"spec": spec_diff}, ns)
        return current

    # -- event mirroring -----------------------------------------------------

    MIRROR_ANNOTATION = "notebooks.kubeflow.org/mirrored-from"
    # Durable record of the last mirroring pass, one Event per notebook:
    # a failed-over leader seeds its throttle window from it, so a restart
    # during an event storm doesn't re-list every event for every notebook
    # at once (VERDICT r1 item 10).  involvedObject is the controller, not
    # the notebook — user event feeds filter by involvedObject and must
    # not see bookkeeping.
    MIRROR_MARKER_SUFFIX = ".mirror-pass"
    # During the event storms mirroring exists to surface (FailedScheduling
    # on exhausted TPU capacity) each event also triggers a reconcile; even
    # with indexed reads the mirror writes would churn.  Bound it: at most
    # one mirroring pass per notebook per window.
    MIRROR_MIN_INTERVAL_SECONDS = 10.0

    def _mirror_events(self, notebook: Resource) -> None:
        """Re-emit Pod/StatefulSet Events onto the Notebook CR so users see
        scheduling failures (FailedScheduling on TPU capacity, image pulls)
        in the UI without inspecting pods — the reference does the same
        (reference notebook_controller.go:94-118, event→notebook mapping
        :608-644).  Idempotent: the mirror's deterministic name encodes the
        source event uid + count, so re-reconciles hit AlreadyExists."""
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        now = time.monotonic()
        last = self._mirror_last.get((ns, name))
        if last is None:
            last = self._seed_mirror_throttle(ns, name, now)
        if last is not None and now - last < self.mirror_min_interval:
            self._mirror_last[(ns, name)] = last
            return  # the periodic resync guarantees a later pass
        self._mirror_last[(ns, name)] = now
        created_ts = deep_get(notebook, "metadata", "creationTimestamp")
        sts_names = _notebook_sts_names(notebook)
        # Field-selected lists per involved object, not one namespace-wide
        # event list: the apiserver indexes Events on involvedObject.*, and
        # an unselected list made every notebook's mirror pass O(all events
        # in the namespace) — quadratic across a fleet wave (bench_scale.py;
        # on 600 notebooks the cold-start passes alone copied 360k events).
        events = []
        try:
            for sts in sorted(sts_names):
                events.extend(self._events_involving(ns, "StatefulSet", sts))
                # ALL worker-pod events of this STS, any ordinal, whether
                # or not the pod still exists (deleted workers' Warnings
                # must keep mirroring) — one prefix-indexed lookup; the
                # client fallback filters a namespace event list exactly
                # like _event_involves_notebook.
                events.extend(self._pod_events_of_sts(ns, sts))
            # Previously-created mirrors (they involve the Notebook) —
            # dedup locally instead of a guaranteed-409 create per
            # mirrored event on every reconcile.
            mirrors = self._events_involving(ns, NOTEBOOK.kind, name)
        except errors.ApiError:
            return
        existing = {name_of(e): e for e in mirrors}
        for ev in events:
            if not _event_involves_notebook(ev, sts_names):
                continue
            # Only events from this notebook's lifetime: a recreated
            # notebook must not inherit its predecessor's failures.
            # events.k8s.io-style events carry eventTime instead of the
            # deprecated first/lastTimestamp; metadata.creationTimestamp is
            # the final fallback so the filter can't be skipped entirely.
            last_ts = (
                ev.get("lastTimestamp")
                or ev.get("firstTimestamp")
                or ev.get("eventTime")
                or deep_get(ev, "metadata", "creationTimestamp")
                or ""
            )
            if created_ts and last_ts and last_ts[:19] < created_ts[:19]:
                continue
            src_uid = deep_get(ev, "metadata", "uid") or _content_hash(
                [ev.get("reason"), ev.get("message"), last_ts]
            )
            # One mirror per source event; count bumps on a recurring source
            # (FailedScheduling retries) update the mirror in place instead
            # of minting a new Event per bump.
            mirror_name = f"{name}.{src_uid[:10]}"
            prior = existing.get(mirror_name)
            if prior is None and any(
                k.startswith(mirror_name + ".") for k in existing
            ):
                # A mirror created under the legacy <name>.<uid>.<count>
                # naming already covers this source event; don't duplicate
                # it — it ages out of etcd on its own.
                continue
            if prior is not None:
                if (prior.get("count", 1), prior.get("lastTimestamp")) != (
                    ev.get("count", 1), last_ts,
                ):
                    # Count bump on the cached read: a two-field merge
                    # patch (no thaw, no RV, conflict-free) instead of a
                    # full-object update of the frozen view.
                    try:
                        self.client.patch(
                            EVENT, name_of(prior),
                            {"count": ev.get("count", 1),
                             "lastTimestamp": last_ts},
                            ns)
                    except errors.ApiError:
                        pass
                continue
            mirror = {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {
                    "name": mirror_name,
                    "namespace": ns,
                    "annotations": {
                        self.MIRROR_ANNOTATION: (
                            f"{ev.get('involvedObject', {}).get('kind', '')}/"
                            f"{ev.get('involvedObject', {}).get('name', '')}"
                        )
                    },
                },
                "involvedObject": {
                    "apiVersion": f"{NOTEBOOK.group}/{NOTEBOOK.version}",
                    "kind": NOTEBOOK.kind,
                    "name": name,
                    "namespace": ns,
                    "uid": meta(notebook).get("uid", ""),
                },
                "reason": ev.get("reason", ""),
                "message": ev.get("message", ""),
                "type": ev.get("type", "Normal"),
                "source": {"component": "notebook-controller"},
                "firstTimestamp": ev.get("firstTimestamp", last_ts),
                "lastTimestamp": last_ts,
                "count": ev.get("count", 1),
            }
            try:
                apply.create(self.client, mirror)
            except errors.AlreadyExists:
                pass
            except errors.ApiError:
                continue
        self._stamp_mirror_marker(ns, name)

    def _seed_mirror_throttle(self, ns: str, name: str, now: float):
        """Cold-start throttle seed for a restarted/failed-over controller:
        one GET of the durable marker Event per cold key (then memory takes
        over), instead of an unthrottled full event list per notebook."""
        try:
            marker = self._get_event(name + self.MIRROR_MARKER_SUFFIX, ns)
        except errors.ApiError:
            return None
        from kubeflow_tpu.platform.controllers.culling import _parse_time

        t = _parse_time(marker.get("lastTimestamp"))
        if t is None:
            return None
        age = max(0.0, time.time() - t.timestamp())
        return now - age

    def _stamp_mirror_marker(self, ns: str, name: str) -> None:
        from datetime import datetime, timezone

        ts = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        marker_name = name + self.MIRROR_MARKER_SUFFIX
        marker = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": marker_name, "namespace": ns},
            "involvedObject": {
                "kind": "Controller",
                "name": "notebook-controller",
                "namespace": ns,
            },
            "reason": "EventMirrorPass",
            "message": f"event mirroring pass for Notebook {name}",
            "type": "Normal",
            "source": {"component": "notebook-controller"},
            "firstTimestamp": ts,
            "lastTimestamp": ts,
            "count": 1,
        }
        try:
            apply.create(self.client, marker)
            return
        except errors.AlreadyExists:
            pass
        except errors.ApiError:
            return
        try:
            prior = self.client.get(EVENT, marker_name, ns)
            self.client.patch(
                EVENT, marker_name,
                {"lastTimestamp": ts,
                 "count": int(prior.get("count", 1)) + 1},
                ns)
        except errors.ApiError:
            pass

    # -- status --------------------------------------------------------------

    def _update_status(self, notebook: Resource, stses: List[Resource]) -> None:
        ns, name = meta(notebook)["namespace"], name_of(notebook)
        pods = self._pods_of(ns, name)
        ready = sum(1 for p in pods if pod_ready(p))
        worker0 = next(
            (p for p in pods if name_of(p) == f"{name}-0"), None
        )
        status: dict = {
            "readyReplicas": ready,
            "replicas": sum(
                deep_get(s, "spec", "replicas", default=0) for s in stses
            ),
        }
        if worker0:
            status["conditions"] = deep_get(worker0, "status", "conditions", default=[])
            cstates = deep_get(worker0, "status", "containerStatuses", default=[])
            if cstates:
                status["containerState"] = cstates[0].get("state", {})
        if notebook.get("status") != status:
            replicas = status["replicas"]
            was_ready = deep_get(notebook, "status", "readyReplicas", default=0)
            if replicas and ready == replicas and was_ready < replicas:
                # First transition to fully-ready: the spawn-to-ready metric
                # (BASELINE.md headline on the platform side).
                created = deep_get(notebook, "metadata", "creationTimestamp")
                elapsed = _seconds_since(created)
                if elapsed is not None:
                    metrics.notebook_spawn_seconds.observe(elapsed)
            # Diff-and-patch the changed subtree: a readiness tick sends
            # {"status":{"readyReplicas":N}} instead of the whole object,
            # and the RV-free merge patch cannot 409 against concurrent
            # spec writes — the hot-path conflict class under chaos.
            patch_status_diff(self.client, NOTEBOOK, notebook, status)


def _seconds_since(timestamp: Optional[str]) -> Optional[float]:
    from kubeflow_tpu.platform.k8s.types import parse_timestamp

    then = parse_timestamp(timestamp)
    if then is None:
        return None
    return max(0.0, time.time() - then)


def pods_to_notebook_requests(obj: Resource) -> List[Request]:
    """Watch mapper: pod events → owning Notebook (by notebook-name label)."""
    labels = deep_get(obj, "metadata", "labels", default={}) or {}
    nb = labels.get(nbapi.LABEL_NOTEBOOK_NAME)
    if not nb:
        return []
    return [Request(deep_get(obj, "metadata", "namespace", default=""), nb)]


def _strip_slice_suffix(sts_name: str) -> str:
    """``nb-s2`` → ``nb`` (multislice STS naming); anything else unchanged."""
    prefix, _, tail = sts_name.rpartition("-")
    if prefix and tail.startswith("s") and tail[1:].isdigit():
        return prefix
    return sts_name


def _notebook_sts_names(notebook: Resource) -> set:
    """The exact StatefulSet names this notebook owns — a sibling notebook
    legally named ``<name>-s1`` must never be treated as one of our slices."""
    name = name_of(notebook)
    tpu = nbapi.tpu_slice_or_none(notebook)
    n_slices = tpu.num_slices if tpu else 1
    return {
        NotebookReconciler.slice_sts_name(name, s) for s in range(n_slices)
    }


def _event_involves_notebook(ev: Resource, sts_names: set) -> bool:
    io = ev.get("involvedObject") or {}
    kind, obj_name = io.get("kind"), io.get("name", "")
    if kind == "StatefulSet":
        return obj_name in sts_names
    if kind == "Pod":
        prefix, _, ordinal = obj_name.rpartition("-")
        return prefix in sts_names and ordinal.isdigit()
    return False


def events_to_notebook_requests(obj: Resource) -> List[Request]:
    """Watch mapper: a k8s Event on a notebook pod/STS → the owning Notebook
    (reference notebook_controller.go:608-644).  Pods named <nb>-<ordinal>
    map by stripping the StatefulSet ordinal; non-notebook hits resolve to
    NotFound in reconcile and are dropped there."""
    ns = deep_get(obj, "metadata", "namespace", default="")
    io = obj.get("involvedObject") or {}
    kind, obj_name = io.get("kind"), io.get("name", "")
    if kind == "StatefulSet":
        reqs = [Request(ns, obj_name)]
        stripped = _strip_slice_suffix(obj_name)
        if stripped != obj_name:
            # Multislice STS <nb>-s<i>: also try the owning notebook; the
            # wrong candidate resolves to NotFound in reconcile and drops.
            reqs.append(Request(ns, stripped))
        return reqs
    if kind == "Pod":
        prefix, _, ordinal = obj_name.rpartition("-")
        if prefix and ordinal.isdigit():
            reqs = [Request(ns, prefix)]
            stripped = _strip_slice_suffix(prefix)
            if stripped != prefix:
                reqs.append(Request(ns, stripped))
            return reqs
    return []


def _pod_notebook_index(pod: Resource) -> List[str]:
    labels = deep_get(pod, "metadata", "labels", default={}) or {}
    nb = labels.get(nbapi.LABEL_NOTEBOOK_NAME)
    ns = deep_get(pod, "metadata", "namespace", default="")
    return [f"{ns}/{nb}"] if nb else []


def _event_involved_index(ev: Resource) -> List[str]:
    io = ev.get("involvedObject") or {}
    kind, name = io.get("kind"), io.get("name")
    ns = deep_get(ev, "metadata", "namespace", default="")
    if not (kind and name):
        return []
    keys = [f"{ns}/{kind}/{name}"]
    if kind == "Pod":
        # Also file pod events under their StatefulSet prefix (name minus
        # the trailing ordinal) so the mirror pass can fetch EVERY worker
        # event of an STS in one lookup — including events whose pod has
        # already been deleted (a scaled-down worker's OOMKilled Warning
        # outlives the pod, and the mirror must not lose it).
        prefix, _, ordinal = name.rpartition("-")
        if prefix and ordinal.isdigit():
            keys.append(f"{ns}/Pod-of/{prefix}")
    return keys


def make_controller(client, **kwargs):
    from kubeflow_tpu.platform.runtime import Controller
    from kubeflow_tpu.platform.runtime.informer import Informer

    # Sharded HA: the coordinator is the Controller's concern, not the
    # reconciler's (which just sees FencingError surface as a Conflict).
    shards = kwargs.pop("shards", None)

    # EVERY watched kind is sourced from an informer cache (controller-
    # runtime's design: all sources go through the manager cache —
    # reference notebook_controller.go:684-733), and reconcile reads
    # pods/StatefulSets/events from the same indexed caches.  The cache
    # applies a delta BEFORE the mapper enqueues, so a reconcile
    # triggered by an event always sees it.  Informer-backed sources also
    # resume watches by resourceVersion, so a bounded watch window's
    # rollover (RestKubeClient closes at 300 s) replays only the missed
    # deltas — a raw client watch re-listed the ENTIRE kind as ADDED
    # every rollover, a full spurious reconcile sweep per kind per window
    # at fleet scale (bench_scale.py --transport http).
    informers = {
        NOTEBOOK: Informer(client, NOTEBOOK),
        POD: Informer(client, POD,
                      indexers={"notebook": _pod_notebook_index}),
        STATEFULSET: Informer(client, STATEFULSET,
                              indexers={"notebook": _pod_notebook_index}),
        SERVICE: Informer(client, SERVICE),
        PODDISRUPTIONBUDGET: Informer(client, PODDISRUPTIONBUDGET),
        EVENT: Informer(client, EVENT,
                        indexers={"involved": _event_involved_index}),
    }
    # The VirtualService kind exists only on Istio clusters: its informer
    # (whose failed cache sync is FATAL at start, unlike the old tolerant
    # raw watch) and its owns-watch are gated exactly like the
    # reconciler's VS writes — USE_ISTIO=false must keep working on a
    # cluster without the CRD.
    use_istio = kwargs.get("use_istio")
    if use_istio is None:
        use_istio = config.env_bool("USE_ISTIO", True)
    # ONE resolution: forward it so the reconciler cannot re-resolve the
    # env differently and split-brain against the informer wiring.
    kwargs["use_istio"] = use_istio
    owns = [STATEFULSET, SERVICE, PODDISRUPTIONBUDGET]
    if use_istio:
        informers[VIRTUALSERVICE] = Informer(client, VIRTUALSERVICE)
        owns.append(VIRTUALSERVICE)
    return Controller(
        "notebook-controller",
        NotebookReconciler(client, informers=informers, **kwargs),
        primary=NOTEBOOK,
        owns=owns,
        watches=[
            (POD, pods_to_notebook_requests),
            (EVENT, events_to_notebook_requests),
        ],
        informers=informers,
        # Fleet gauges (notebook_running, tpu_chips_requested) are computed
        # at scrape time over this client — one list per scrape, not per
        # reconcile; hooked/unhooked with the controller lifecycle so a
        # stopped controller's client is never scraped.
        on_start=lambda: metrics.register_fleet_collector(client),
        on_stop=lambda: metrics.register_fleet_collector(None),
        # Safety net for drift no watch covers (and for the REST client's
        # bounded watch windows): re-list the primaries periodically.
        resync_period=300.0,
        shards=shards,
        # Server-side shard subscriptions for the watches-sourced kinds:
        # pods carry their notebook's name in the statefulset-template
        # label, which is exactly how pods_to_notebook_requests maps
        # them; events shard on their involvedObject's name candidates
        # (name, ordinal-stripped, slice-stripped — a superset of what
        # events_to_notebook_requests resolves, so the wire filter only
        # ever removes events admit would also drop).
        shard_sources={POD: f"label={nbapi.LABEL_NOTEBOOK_NAME}",
                       EVENT: "involved"},
    )
