"""Tensorboard controller: Tensorboard CR → Deployment + Service + VirtualService.

Mirrors the reference behavior (reference tensorboard_controller.go:67-240):
``spec.logspath`` selects the log source — ``pvc://claim/subpath`` mounts the
claim, ``gs://`` paths mount GCP credentials when a ``user-gcp-sa`` secret
exists — and RWO_PVC_SCHEDULING co-schedules with the pod already mounting a
RWO claim.  TPU-native addition: the image default serves TensorBoard with
the JAX profiler plugin, so XLA/TPU traces dumped from notebooks
(jax.profiler.trace) open directly.
"""
from __future__ import annotations

from typing import Optional

from kubeflow_tpu.platform import config
from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    DEPLOYMENT,
    SECRET,
    SERVICE,
    TENSORBOARD,
    VIRTUALSERVICE,
    Resource,
    deep_get,
    meta,
    name_of,
    set_owner,
)
from kubeflow_tpu.platform.runtime import Reconciler, Request, Result

DEFAULT_IMAGE = "tensorflow/tensorflow:2.15.0"
GCP_SECRET = "user-gcp-sa"


class TensorboardReconciler(Reconciler):
    def __init__(self, client, *, image: Optional[str] = None,
                 cluster_domain: Optional[str] = None,
                 istio_gateway: Optional[str] = None,
                 rwo_pvc_scheduling: Optional[bool] = None):
        self.client = client
        self.image = image or config.env("TENSORBOARD_IMAGE", DEFAULT_IMAGE)
        self.cluster_domain = cluster_domain or config.env("CLUSTER_DOMAIN", "cluster.local")
        self.istio_gateway = istio_gateway or config.env(
            "ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"
        )
        self.rwo_pvc_scheduling = (
            rwo_pvc_scheduling
            if rwo_pvc_scheduling is not None
            else config.env_bool("RWO_PVC_SCHEDULING", False)
        )

    def reconcile(self, req: Request) -> Optional[Result]:
        try:
            tb = self.client.get(TENSORBOARD, req.name, req.namespace)
        except errors.NotFound:
            return None
        from kubeflow_tpu.platform.runtime.apply import create_or_update

        create_or_update(self.client, DEPLOYMENT, self.generate_deployment(tb))
        create_or_update(self.client, SERVICE, self.generate_service(tb))
        create_or_update(self.client, VIRTUALSERVICE, self.generate_virtual_service(tb))
        self._update_status(tb)
        return None

    # -- generation ----------------------------------------------------------

    def generate_deployment(self, tb: Resource) -> Resource:
        ns, name = meta(tb)["namespace"], name_of(tb)
        logspath = deep_get(tb, "spec", "logspath", default="") or ""
        volumes = []
        mounts = []
        env = []
        logdir = logspath
        if logspath.startswith("pvc://"):
            rest = logspath[len("pvc://"):]
            claim, _, subpath = rest.partition("/")
            volumes.append({
                "name": "logs",
                "persistentVolumeClaim": {"claimName": claim},
            })
            mounts.append({"name": "logs", "mountPath": "/logs",
                           **({"subPath": subpath} if subpath else {})})
            logdir = "/logs"
        elif logspath.startswith("gs://") and self._gcp_secret_exists(ns):
            volumes.append({
                "name": "gcp-creds", "secret": {"secretName": GCP_SECRET},
            })
            mounts.append({"name": "gcp-creds",
                           "mountPath": "/secret/gcp", "readOnly": True})
            env.append({
                "name": "GOOGLE_APPLICATION_CREDENTIALS",
                "value": f"/secret/gcp/{GCP_SECRET}.json",
            })
        pod_spec: dict = {
            "containers": [{
                "name": "tensorboard",
                "image": self.image,
                "command": ["/usr/local/bin/tensorboard"],
                "args": [
                    f"--logdir={logdir}",
                    "--bind_all",
                    f"--path_prefix=/tensorboard/{ns}/{name}",
                ],
                "ports": [{"containerPort": 6006}],
                "env": env,
                "volumeMounts": mounts,
            }],
            "volumes": volumes,
        }
        if self.rwo_pvc_scheduling and logspath.startswith("pvc://"):
            claim = logspath[len("pvc://"):].partition("/")[0]
            affinity = self._rwo_affinity(ns, claim)
            if affinity:
                pod_spec["affinity"] = affinity
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": name}},
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": pod_spec,
                },
            },
        }
        set_owner(deployment, tb)
        return deployment

    def _gcp_secret_exists(self, ns: str) -> bool:
        try:
            self.client.get(SECRET, GCP_SECRET, ns)
            return True
        except errors.NotFound:
            return False

    def _rwo_affinity(self, ns: str, claim: str) -> Optional[dict]:
        """Pin to the node already mounting the RWO claim (reference
        :168-240): find a running pod using the claim, prefer its node."""
        from kubeflow_tpu.platform.k8s.types import POD

        for pod in self.client.list(POD, ns):
            for vol in deep_get(pod, "spec", "volumes", default=[]) or []:
                if deep_get(vol, "persistentVolumeClaim", "claimName") == claim:
                    node = deep_get(pod, "spec", "nodeName")
                    if node:
                        return {"nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [{
                                    "matchExpressions": [{
                                        "key": "kubernetes.io/hostname",
                                        "operator": "In",
                                        "values": [node],
                                    }]
                                }]
                            }
                        }}
        return None

    def generate_service(self, tb: Resource) -> Resource:
        ns, name = meta(tb)["namespace"], name_of(tb)
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "selector": {"app": name},
                "ports": [{"name": "http-tb", "port": 80, "targetPort": 6006}],
            },
        }
        set_owner(svc, tb)
        return svc

    def generate_virtual_service(self, tb: Resource) -> Resource:
        ns, name = meta(tb)["namespace"], name_of(tb)
        vs = {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"tensorboard-{ns}-{name}", "namespace": ns},
            "spec": {
                "hosts": ["*"],
                "gateways": [self.istio_gateway],
                "http": [{
                    "match": [{"uri": {"prefix": f"/tensorboard/{ns}/{name}/"}}],
                    "route": [{"destination": {
                        "host": f"{name}.{ns}.svc.{self.cluster_domain}",
                        "port": {"number": 80},
                    }}],
                }],
            },
        }
        set_owner(vs, tb)
        return vs

    # -- plumbing ------------------------------------------------------------

    def _update_status(self, tb: Resource) -> None:
        ns, name = meta(tb)["namespace"], name_of(tb)
        try:
            deployment = self.client.get(DEPLOYMENT, name, ns)
        except errors.NotFound:
            return
        conditions = deep_get(deployment, "status", "conditions", default=[])
        ready = deep_get(deployment, "status", "readyReplicas", default=0)
        status = {"conditions": conditions, "readyReplicas": ready}
        # Diff-and-patch: only the changed status subtree crosses the wire,
        # with no resourceVersion to conflict on (runtime/apply.py).
        from kubeflow_tpu.platform.runtime.apply import patch_status_diff

        patch_status_diff(self.client, TENSORBOARD, tb, status)


def make_controller(client, **kwargs):
    from kubeflow_tpu.platform.runtime import Controller

    shards = kwargs.pop("shards", None)
    return Controller(
        "tensorboard-controller",
        TensorboardReconciler(client, **kwargs),
        primary=TENSORBOARD,
        owns=[DEPLOYMENT, SERVICE, VIRTUALSERVICE],
        # Deliberately NO primary informer: the Tensorboard CRD is
        # optional, and an informer's failed cache sync is FATAL at
        # Controller.start (it would take the whole manager down on a
        # cluster without the CRD), where the raw watch just retries.
        # The raw watch resumes by resourceVersion (_watch_loop), so the
        # bounded-window full-replay cost the informer would have fixed
        # is fixed anyway.
        resync_period=300.0,
        shards=shards,
    )
