"""Loader for the native control-plane library (libkfnative.so).

The platform's hot paths have native C++ implementations (``native/`` at the
repo root) mirroring the role of the reference's compiled Go binaries
(SURVEY.md §2: controllers/webhook are Go; this build's runtime language is
C++ + Python):

* ``kfp_*`` — JSON parse/serialize + RFC 6902 patch create/apply, used by the
  admission webhook to diff pods (reference admission-webhook/main.go:683-695).
* ``kfq_*`` — delaying rate-limited workqueue used by the controller runtime
  (reference vendored client-go util/workqueue).

Loading is best-effort: if the shared library is absent we attempt one
``make -C native`` (g++ is in the image); on any failure the pure-Python
implementations are used.  ``KF_NATIVE=0`` disables the native path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "kubeflow_tpu", "_native", "libkfnative.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_lock = threading.Lock()


def _try_build() -> bool:
    makefile = os.path.join(_REPO_ROOT, "native", "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native")],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    with _load_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        from kubeflow_tpu.platform import config

        if config.knob("KF_NATIVE", "1",
                       doc="'0' disables the native C++ engine") == "0":
            return None
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        if not hasattr(lib, "kfq_is_processing"):  # newest required symbol
            # Stale prebuilt library from before a symbol was added.
            # Rebuild for FUTURE processes (make re-links, sources are
            # newer) but report unavailable now — dlopen caches the mapped
            # object by path, so re-CDLL'ing in this process would return
            # the stale mapping anyway.  Python fallbacks engage.
            _try_build()
            return None
        # kfp: JSON patch engine
        lib.kfp_create_patch.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_create_patch.restype = ctypes.c_void_p
        lib.kfp_apply_patch.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_apply_patch.restype = ctypes.c_void_p
        lib.kfp_canonical.argtypes = [ctypes.c_char_p]
        lib.kfp_canonical.restype = ctypes.c_void_p
        lib.kfp_merge_apply.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_merge_apply.restype = ctypes.c_void_p
        lib.kfp_merge_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_merge_create.restype = ctypes.c_void_p
        lib.kfp_last_error.argtypes = []
        lib.kfp_last_error.restype = ctypes.c_char_p
        lib.kfp_free.argtypes = [ctypes.c_void_p]
        lib.kfp_free.restype = None
        # kfq: workqueue
        lib.kfq_new.argtypes = [ctypes.c_double, ctypes.c_double]
        lib.kfq_new.restype = ctypes.c_void_p
        lib.kfq_delete.argtypes = [ctypes.c_void_p]
        lib.kfq_add.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_double]
        lib.kfq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_forget.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_failures.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_failures.restype = ctypes.c_int
        lib.kfq_is_pending.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_pending.restype = ctypes.c_int
        lib.kfq_get.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.kfq_get.restype = ctypes.c_int64
        lib.kfq_done.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_processing.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_processing.restype = ctypes.c_int
        lib.kfq_pending.argtypes = [ctypes.c_void_p]
        lib.kfq_pending.restype = ctypes.c_int64
        lib.kfq_shutdown.argtypes = [ctypes.c_void_p]
        # kfpk: sequence packer
        _i64p = ctypes.POINTER(ctypes.c_int64)
        lib.kfpk_pack.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int64,
                                  _i64p, _i64p]
        lib.kfpk_pack.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def loaded() -> bool:
    """True only if the library is ALREADY loaded — never triggers the
    first-use build (which can block ~2 min).  For callers on latency-
    sensitive or lock-holding paths (FakeKube.patch) where the Python
    fallback is preferable to waiting on make."""
    return _lib is not None


def preload() -> bool:
    """Eagerly load (and if needed build) the native library.

    Call at process startup — webhook server boot, Manager construction —
    so the one-time ``make`` (up to ~2 min on first deploy) never lands on
    a request path: admission webhooks time out at 10-30 s.
    """
    return available()


def backend_info() -> str:
    return f"native:{_LIB_PATH}" if available() else "python"


# -- JSON patch ---------------------------------------------------------------


class NativeError(Exception):
    pass


def _call_str(fn, *args: bytes) -> str:
    lib = _load()
    assert lib is not None
    ptr = fn(*args)
    if not ptr:
        raise NativeError(lib.kfp_last_error().decode())
    try:
        return ctypes.cast(ptr, ctypes.c_char_p).value.decode()  # type: ignore[union-attr]
    finally:
        lib.kfp_free(ptr)


def create_patch_json(before_json: str, after_json: str) -> str:
    """RFC 6902 diff of two JSON document strings (native)."""
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_create_patch, before_json.encode(), after_json.encode())


def apply_patch_json(doc_json: str, patch_json: str) -> str:
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_apply_patch, doc_json.encode(), patch_json.encode())


def create_patch(before: Any, after: Any) -> List[Dict[str, Any]]:
    """Object-level convenience wrapper (json round-trip at the boundary)."""
    import json

    return json.loads(create_patch_json(json.dumps(before), json.dumps(after)))


def apply_patch(doc: Any, ops: List[Dict[str, Any]]) -> Any:
    import json

    return json.loads(apply_patch_json(json.dumps(doc), json.dumps(ops)))


# -- RFC 7386 merge patch -----------------------------------------------------


def merge_patch_apply(doc: Any, patch: Any) -> Any:
    """Apply a JSON merge patch (native engine; json at the boundary)."""
    import json

    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    out = _call_str(lib.kfp_merge_apply, json.dumps(doc).encode(),
                    json.dumps(patch).encode())
    return json.loads(out)


def merge_patch_create(before: Any, after: Any) -> Any:
    """Diff two documents into the merge patch turning before into after."""
    import json

    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    out = _call_str(lib.kfp_merge_create, json.dumps(before).encode(),
                    json.dumps(after).encode())
    return json.loads(out)


# -- workqueue ----------------------------------------------------------------


class NativeWorkQueue:
    """ctypes wrapper over kfq_* keeping the Python _WorkQueue interface.

    Maps hashable request objects <-> int64 keys at the boundary; the
    queueing itself (heap, dedup, backoff) runs in C++.  ``metrics`` is the
    shared WorkQueueMetrics shim (runtime/metrics.py) — hooks fire at the
    same semantic points as _WorkQueue's so the workqueue_* series are in
    parity across engines; timing state lives in the shim because the C++
    queue's internals are opaque here.
    """

    def __init__(self, *, base_delay: float = 0.05, max_delay: float = 30.0,
                 metrics=None):
        lib = _load()
        if lib is None:
            raise NativeError("native library unavailable")
        self._lib = lib
        self._q = lib.kfq_new(base_delay, max_delay)
        self._base = base_delay
        self._max = max_delay
        self.metrics = metrics
        # Mirrors the C++ shutdown_ flag (only this wrapper's shut_down()
        # sets it): the engine silently drops adds after shutdown, so the
        # metric hooks must not fire for them — _WorkQueue guards the same
        # way, and the shim's cross-engine parity depends on it.
        self._shutdown = False
        self._lock = threading.Lock()
        self._next_id = 0
        self._to_id: Dict[Any, int] = {}
        self._from_id: Dict[int, Any] = {}

    def _key_locked(self, req: Any) -> int:
        key = self._to_id.get(req)
        if key is None:
            key = self._next_id
            self._next_id += 1
            self._to_id[req] = key
            self._from_id[key] = req
        return key

    # Mapping mutations and the C enqueue run under one Python lock.
    # kfq_get deliberately blocks OUTSIDE that lock, so done()'s prune must
    # check kfq_is_processing: another worker may have popped this key
    # between our kfq_done and the prune check (a real race, reproduced in
    # review r2 — 10 orphaned ids in ~10k get/done cycles without it).

    def add(self, req: Any, *, delay: float = 0.0) -> None:
        with self._lock:
            if self._shutdown:
                return
            if self.metrics is not None:
                self.metrics.on_add(req, delay=delay)
            self._lib.kfq_add(self._q, self._key_locked(req), delay)

    def add_rate_limited(self, req: Any) -> None:
        with self._lock:
            if self._shutdown:
                return
            key = self._key_locked(req)
            if self.metrics is not None:
                # Mirror the C++ backoff (min(base * 2^failures, max)) so
                # the shim's eligible-time bookkeeping matches what the
                # engine will actually schedule.
                n = self._lib.kfq_failures(self._q, key)
                self.metrics.on_retry(req)
                self.metrics.on_add(
                    req, delay=min(self._base * (2 ** n), self._max))
            self._lib.kfq_add_rate_limited(self._q, key)

    def forget(self, req: Any) -> None:
        with self._lock:
            key = self._to_id.get(req)
            if key is not None:
                self._lib.kfq_forget(self._q, key)

    def failures(self, req: Any) -> int:
        with self._lock:
            key = self._to_id.get(req)
            return self._lib.kfq_failures(self._q, key) if key is not None else 0

    def get(self, timeout: float = 0.2) -> Optional[Any]:
        """Pop a key, taking the per-key exclusion.  The caller MUST pair
        every non-None return with done(key) (in a finally); otherwise
        re-adds park in the dirty set and the key is never delivered
        again (client-go workqueue contract)."""
        key = self._lib.kfq_get(self._q, timeout)  # blocking: outside the lock
        if key < 0:
            return None
        with self._lock:
            req = self._from_id.get(key)
            # on_get under the SAME lock as add()'s on_add, like
            # _WorkQueue.  One residual skew the wrapper cannot close: the
            # C++ pop happens outside this lock, so an add(key) landing in
            # the microseconds before the hook runs merges into the entry
            # on_get consumes.  "Earliest eligible wins" keeps THIS
            # delivery's wait correct; the racing re-add's own wait is
            # later observed as ~0s (its entry was consumed here).  Making
            # it exact needs kfq_get to return the enqueue timestamp —
            # not worth the ABI change for a µs-window histogram skew.
            if req is not None and self.metrics is not None:
                self.metrics.on_get(req)
        return req

    def done(self, req: Any) -> None:
        """Release the per-key exclusion taken by get().  Also the point
        where the id maps stay bounded: drop the mapping once the key has
        no pending/dirty entry and no backoff state — a later add() simply
        assigns a fresh id."""
        with self._lock:
            key = self._to_id.get(req)
            if key is None:
                return
            if self.metrics is not None and self._lib.kfq_is_processing(
                    self._q, key):
                self.metrics.on_done(req)
            self._lib.kfq_done(self._q, key)
            if (
                not self._lib.kfq_is_pending(self._q, key)
                and not self._lib.kfq_is_processing(self._q, key)
                and self._lib.kfq_failures(self._q, key) == 0
            ):
                del self._to_id[req]
                del self._from_id[key]

    def pending(self) -> int:
        return int(self._lib.kfq_pending(self._q))

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
        self._lib.kfq_shutdown(self._q)

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.kfq_delete(self._q)
                self._q = None
        except Exception:  # kft: disable=R006 interpreter-shutdown __del__: modules may be torn down, logging unsafe
            pass


# -- sequence packer ----------------------------------------------------------


def native_pack(lengths, row_len: int):
    """Best-fit-decreasing packing via the C++ engine.

    ``lengths``: int64 numpy array of document lengths.  Returns
    ``(row_assignment, row_offset, n_rows)`` int64 arrays, or None when the
    native library is unavailable (caller uses the Python fallback).
    Raises ValueError for invalid lengths (the engine's -1)."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np

    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    n = len(lengths)
    assignment = np.empty(n, dtype=np.int64)
    offset = np.empty(n, dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    rows = lib.kfpk_pack(
        lengths.ctypes.data_as(i64p), n, int(row_len),
        assignment.ctypes.data_as(i64p), offset.ctypes.data_as(i64p),
    )
    if rows < 0:
        raise ValueError(
            f"invalid document lengths for row_len={row_len}"
        )
    return assignment, offset, int(rows)
