"""Loader for the native control-plane library (libkfnative.so).

The platform's hot paths have native C++ implementations (``native/`` at the
repo root) mirroring the role of the reference's compiled Go binaries
(SURVEY.md §2: controllers/webhook are Go; this build's runtime language is
C++ + Python):

* ``kfp_*`` — JSON parse/serialize + RFC 6902 patch create/apply, used by the
  admission webhook to diff pods (reference admission-webhook/main.go:683-695).
* ``kfq_*`` — delaying rate-limited workqueue used by the controller runtime
  (reference vendored client-go util/workqueue).
* ``kfw_*`` — watch-event envelope scanner for the wire codec fast path
  (k8s/codec.py): locates type/object/metadata byte ranges so the informer
  defers full-body decode until an event is actually admitted.

Loading is best-effort: if the shared library is absent we attempt one
``make -C native`` (g++ is in the image) — and only one: build failure is
cached for the life of the process and every caller sticks to the
pure-Python implementations (``load_error()`` says why, /healthz carries
the engine string).  ``KF_NATIVE=0`` disables the native path.
"""
from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "kubeflow_tpu", "_native", "libkfnative.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_load_lock = threading.Lock()
_load_error: Optional[str] = None

# Everything the one shared library serves; the per-component breakdown
# exists because /metrics wants native_engine_active{component="..."} even
# though today the components load (or fail) as one unit.
ENGINE_COMPONENTS = ("jsonpatch", "workqueue", "packer", "wirecodec")


def _try_build() -> bool:
    makefile = os.path.join(_REPO_ROOT, "native", "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native")],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _knob_native() -> str:
    from kubeflow_tpu.platform import config

    try:
        return config.knob(
            "KF_NATIVE", "1",
            doc="'0' disables the native C++ engine, '1' enables it",
            validate=lambda v: None if v in ("0", "1")
            else "must be '0' or '1'")
    except ValueError:
        # Strict knob: the bad env value is surfaced at /debug/knobs
        # (source=env-invalid); the engine itself keeps the default.
        return "1"


def _set_engine_gauge(active: bool) -> None:
    try:
        from kubeflow_tpu.platform.runtime import metrics

        for component in ENGINE_COMPONENTS:
            metrics.native_engine_active.labels(
                component=component).set(1.0 if active else 0.0)
    except Exception:  # kft: disable=R006 metrics best-effort at load time
        pass


def _finish_load(lib: Optional[ctypes.CDLL], error: Optional[str]
                 ) -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    _lib = lib
    _load_error = error
    _set_engine_gauge(lib is not None)
    return _lib


def _load() -> Optional[ctypes.CDLL]:
    global _load_attempted
    if _load_attempted:
        return _lib
    with _load_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if _knob_native() == "0":
            return _finish_load(None, "disabled by KF_NATIVE=0")
        if not os.path.exists(_LIB_PATH) and not _try_build():
            # The single build attempt this process gets: from here on
            # every component answers from the Python fallback without
            # re-invoking make.
            return _finish_load(None, "build failed or unavailable")
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            return _finish_load(None, f"dlopen failed: {e}")
        if not hasattr(lib, "kfw_scan_event"):  # newest required symbol
            # Stale prebuilt library from before a symbol was added.
            # Rebuild for FUTURE processes (make re-links, sources are
            # newer) but report unavailable now — dlopen caches the mapped
            # object by path, so re-CDLL'ing in this process would return
            # the stale mapping anyway.  Python fallbacks engage.
            _try_build()
            return _finish_load(None, "stale library (missing kfw_scan_event)")
        # kfp: JSON patch engine
        lib.kfp_create_patch.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_create_patch.restype = ctypes.c_void_p
        lib.kfp_apply_patch.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_apply_patch.restype = ctypes.c_void_p
        lib.kfp_canonical.argtypes = [ctypes.c_char_p]
        lib.kfp_canonical.restype = ctypes.c_void_p
        lib.kfp_merge_apply.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_merge_apply.restype = ctypes.c_void_p
        lib.kfp_merge_create.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
        lib.kfp_merge_create.restype = ctypes.c_void_p
        lib.kfp_last_error.argtypes = []
        lib.kfp_last_error.restype = ctypes.c_char_p
        lib.kfp_free.argtypes = [ctypes.c_void_p]
        lib.kfp_free.restype = None
        # kfq: workqueue
        lib.kfq_new.argtypes = [ctypes.c_double, ctypes.c_double]
        lib.kfq_new.restype = ctypes.c_void_p
        lib.kfq_delete.argtypes = [ctypes.c_void_p]
        lib.kfq_add.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_double]
        lib.kfq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_forget.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_failures.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_failures.restype = ctypes.c_int
        lib.kfq_is_pending.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_pending.restype = ctypes.c_int
        lib.kfq_get.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.kfq_get.restype = ctypes.c_int64
        lib.kfq_done.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_processing.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kfq_is_processing.restype = ctypes.c_int
        lib.kfq_pending.argtypes = [ctypes.c_void_p]
        lib.kfq_pending.restype = ctypes.c_int64
        lib.kfq_shutdown.argtypes = [ctypes.c_void_p]
        # kfpk: sequence packer
        _i64p = ctypes.POINTER(ctypes.c_int64)
        lib.kfpk_pack.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int64,
                                  _i64p, _i64p]
        lib.kfpk_pack.restype = ctypes.c_int64
        # kfw: wire codec
        lib.kfw_scan_event.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       _i64p]
        lib.kfw_scan_event.restype = ctypes.c_int
        lib.kfw_last_error.argtypes = []
        lib.kfw_last_error.restype = ctypes.c_char_p
        return _finish_load(lib, None)


def available() -> bool:
    return _load() is not None


def loaded() -> bool:
    """True only if the library is ALREADY loaded — never triggers the
    first-use build (which can block ~2 min).  For callers on latency-
    sensitive or lock-holding paths (FakeKube.patch) where the Python
    fallback is preferable to waiting on make."""
    return _lib is not None


def preload() -> bool:
    """Eagerly load (and if needed build) the native library.

    Call at process startup — webhook server boot, Manager construction —
    so the one-time ``make`` (up to ~2 min on first deploy) never lands on
    a request path: admission webhooks time out at 10-30 s.
    """
    return available()


def backend_info() -> str:
    return f"native:{_LIB_PATH}" if available() else "python"


def load_error() -> Optional[str]:
    """Why the native engine is NOT active (None while active or before
    the first load attempt).  Surfaced next to the engine string in
    /healthz so a fleet stuck on the Python fallback is diagnosable."""
    return _load_error


def engine_components() -> Dict[str, bool]:
    """Per-component engine state, the native_engine_active gauge's
    source of truth (the components ship in one .so, so they activate or
    fail together — the breakdown keeps the metric stable if that ever
    changes)."""
    active = available()
    return {c: active for c in ENGINE_COMPONENTS}


# -- JSON patch ---------------------------------------------------------------


class NativeError(Exception):
    pass


def _call_str(fn, *args: bytes) -> str:
    lib = _load()
    assert lib is not None
    ptr = fn(*args)
    if not ptr:
        raise NativeError(lib.kfp_last_error().decode())
    try:
        return ctypes.cast(ptr, ctypes.c_char_p).value.decode()  # type: ignore[union-attr]
    finally:
        lib.kfp_free(ptr)


def create_patch_json(before_json: str, after_json: str) -> str:
    """RFC 6902 diff of two JSON document strings (native)."""
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_create_patch, before_json.encode(), after_json.encode())


def apply_patch_json(doc_json: str, patch_json: str) -> str:
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_apply_patch, doc_json.encode(), patch_json.encode())


def create_patch(before: Any, after: Any) -> List[Dict[str, Any]]:
    """Object-level convenience wrapper (json round-trip at the boundary)."""
    import json

    return json.loads(create_patch_json(json.dumps(before), json.dumps(after)))


def apply_patch(doc: Any, ops: List[Dict[str, Any]]) -> Any:
    import json

    return json.loads(apply_patch_json(json.dumps(doc), json.dumps(ops)))


# -- RFC 7386 merge patch -----------------------------------------------------


def merge_patch_apply(doc: Any, patch: Any) -> Any:
    """Apply a JSON merge patch (native engine; json at the boundary)."""
    import json

    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    out = _call_str(lib.kfp_merge_apply, json.dumps(doc).encode(),
                    json.dumps(patch).encode())
    return json.loads(out)


def merge_patch_create_json(before_json: str, after_json: str) -> str:
    """String-boundary variant of merge_patch_create for callers that
    already hold serialized documents (the wire codec): no Python-side
    json round trip on the inputs."""
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_merge_create, before_json.encode(),
                     after_json.encode())


def merge_patch_create(before: Any, after: Any) -> Any:
    """Diff two documents into the merge patch turning before into after."""
    import json

    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    out = _call_str(lib.kfp_merge_create, json.dumps(before).encode(),
                    json.dumps(after).encode())
    return json.loads(out)


def canonical_json(doc_json: str) -> str:
    """Parse + re-serialize a JSON document through the native engine's
    Python-compatible compact serializer (byte-equal to
    ``json.dumps(obj, separators=(",", ":"))``)."""
    lib = _load()
    if lib is None:
        raise NativeError("native library unavailable")
    return _call_str(lib.kfp_canonical, doc_json.encode())


# -- wire codec ---------------------------------------------------------------


# One unpack of the whole 12-slot out array beats twelve ctypes
# __getitem__ calls — the wrapper overhead is most of what separates the
# native decode path from the 3x band (bench_scale's decode A/B).
_KFW_UNPACK = struct.Struct("=12q").unpack_from

WireScan = Tuple[str, bytes, Optional[bytes],
                 Optional[str], Optional[str], Optional[str]]


def wire_scanner() -> Optional[Callable[[bytes], WireScan]]:
    """Bind the native envelope scanner into a fast per-caller closure.

    Returns None when the library is unavailable.  The closure takes one
    watch line and returns ``(etype, object_bytes, metadata_bytes_or_None,
    name, namespace, resourceVersion)``; the trailing three are the
    metadata identity fields when the scanner could extract them
    (escape-free strings), else None — None means "parse the metadata
    slice to find out", never "absent".  Raises NativeError when the line
    does not scan.

    The closure owns its out-buffer, so it is NOT thread-safe: hold one
    closure per thread (the codec keeps them in a threading.local).
    Binding everything per-closure keeps the per-event cost to one
    ctypes call plus one struct unpack.
    """
    lib = _load()
    if lib is None:
        return None
    scan = lib.kfw_scan_event
    last_error = lib.kfw_last_error
    out = (ctypes.c_int64 * 12)()
    unpack = _KFW_UNPACK

    def _scan(line: bytes) -> WireScan:
        if scan(line, len(line), out) != 0:
            raise NativeError(last_error().decode())
        (ts, te, os_, oe, ms, me,
         ns_s, ns_e, sp_s, sp_e, rv_s, rv_e) = unpack(out)
        return (
            line[ts:te].decode(),
            line[os_:oe],
            line[ms:me] if ms >= 0 else None,
            line[ns_s:ns_e].decode() if ns_s >= 0 else None,
            line[sp_s:sp_e].decode() if sp_s >= 0 else None,
            line[rv_s:rv_e].decode() if rv_s >= 0 else None,
        )

    return _scan


def wire_scan_event(line: bytes):
    """Scan one watch line's envelope natively.

    Returns ``(etype, object_bytes, metadata_bytes_or_None)`` — the slices
    of ``line`` holding the event type, the full object value, and the
    object's top-level metadata value.  Raises NativeError when the
    library is unavailable or the line does not scan (the codec falls
    back to json.loads on the whole line).  Convenience form of
    :func:`wire_scanner` for tests and one-off callers."""
    scanner = wire_scanner()
    if scanner is None:
        raise NativeError("native library unavailable")
    etype, obj, meta, _, _, _ = scanner(line)
    return etype, obj, meta


# -- workqueue ----------------------------------------------------------------


class NativeWorkQueue:
    """ctypes wrapper over kfq_* keeping the Python _WorkQueue interface.

    Maps hashable request objects <-> int64 keys at the boundary; the
    queueing itself (heap, dedup, backoff) runs in C++.  ``metrics`` is the
    shared WorkQueueMetrics shim (runtime/metrics.py) — hooks fire at the
    same semantic points as _WorkQueue's so the workqueue_* series are in
    parity across engines; timing state lives in the shim because the C++
    queue's internals are opaque here.
    """

    def __init__(self, *, base_delay: float = 0.05, max_delay: float = 30.0,
                 metrics=None):
        lib = _load()
        if lib is None:
            raise NativeError("native library unavailable")
        self._lib = lib
        self._q = lib.kfq_new(base_delay, max_delay)
        self._base = base_delay
        self._max = max_delay
        self.metrics = metrics
        # Mirrors the C++ shutdown_ flag (only this wrapper's shut_down()
        # sets it): the engine silently drops adds after shutdown, so the
        # metric hooks must not fire for them — _WorkQueue guards the same
        # way, and the shim's cross-engine parity depends on it.
        self._shutdown = False
        self._lock = threading.Lock()
        self._next_id = 0
        self._to_id: Dict[Any, int] = {}
        self._from_id: Dict[int, Any] = {}

    def _key_locked(self, req: Any) -> int:
        key = self._to_id.get(req)
        if key is None:
            key = self._next_id
            self._next_id += 1
            self._to_id[req] = key
            self._from_id[key] = req
        return key

    # Mapping mutations and the C enqueue run under one Python lock.
    # kfq_get deliberately blocks OUTSIDE that lock, so done()'s prune must
    # check kfq_is_processing: another worker may have popped this key
    # between our kfq_done and the prune check (a real race, reproduced in
    # review r2 — 10 orphaned ids in ~10k get/done cycles without it).

    def add(self, req: Any, *, delay: float = 0.0) -> None:
        with self._lock:
            if self._shutdown:
                return
            if self.metrics is not None:
                self.metrics.on_add(req, delay=delay)
            self._lib.kfq_add(self._q, self._key_locked(req), delay)

    def add_rate_limited(self, req: Any) -> None:
        with self._lock:
            if self._shutdown:
                return
            key = self._key_locked(req)
            if self.metrics is not None:
                # Mirror the C++ backoff (min(base * 2^failures, max)) so
                # the shim's eligible-time bookkeeping matches what the
                # engine will actually schedule.
                n = self._lib.kfq_failures(self._q, key)
                self.metrics.on_retry(req)
                self.metrics.on_add(
                    req, delay=min(self._base * (2 ** n), self._max))
            self._lib.kfq_add_rate_limited(self._q, key)

    def forget(self, req: Any) -> None:
        with self._lock:
            key = self._to_id.get(req)
            if key is not None:
                self._lib.kfq_forget(self._q, key)

    def failures(self, req: Any) -> int:
        with self._lock:
            key = self._to_id.get(req)
            return self._lib.kfq_failures(self._q, key) if key is not None else 0

    def get(self, timeout: float = 0.2) -> Optional[Any]:
        """Pop a key, taking the per-key exclusion.  The caller MUST pair
        every non-None return with done(key) (in a finally); otherwise
        re-adds park in the dirty set and the key is never delivered
        again (client-go workqueue contract)."""
        key = self._lib.kfq_get(self._q, timeout)  # blocking: outside the lock
        if key < 0:
            return None
        with self._lock:
            req = self._from_id.get(key)
            # on_get under the SAME lock as add()'s on_add, like
            # _WorkQueue.  One residual skew the wrapper cannot close: the
            # C++ pop happens outside this lock, so an add(key) landing in
            # the microseconds before the hook runs merges into the entry
            # on_get consumes.  "Earliest eligible wins" keeps THIS
            # delivery's wait correct; the racing re-add's own wait is
            # later observed as ~0s (its entry was consumed here).  Making
            # it exact needs kfq_get to return the enqueue timestamp —
            # not worth the ABI change for a µs-window histogram skew.
            if req is not None and self.metrics is not None:
                self.metrics.on_get(req)
        return req

    def done(self, req: Any) -> None:
        """Release the per-key exclusion taken by get().  Also the point
        where the id maps stay bounded: drop the mapping once the key has
        no pending/dirty entry and no backoff state — a later add() simply
        assigns a fresh id."""
        with self._lock:
            key = self._to_id.get(req)
            if key is None:
                return
            if self.metrics is not None and self._lib.kfq_is_processing(
                    self._q, key):
                self.metrics.on_done(req)
            self._lib.kfq_done(self._q, key)
            if (
                not self._lib.kfq_is_pending(self._q, key)
                and not self._lib.kfq_is_processing(self._q, key)
                and self._lib.kfq_failures(self._q, key) == 0
            ):
                del self._to_id[req]
                del self._from_id[key]

    def pending(self) -> int:
        return int(self._lib.kfq_pending(self._q))

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
        self._lib.kfq_shutdown(self._q)

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.kfq_delete(self._q)
                self._q = None
        except Exception:  # kft: disable=R006 interpreter-shutdown __del__: modules may be torn down, logging unsafe
            pass


# -- sequence packer ----------------------------------------------------------


def native_pack(lengths, row_len: int):
    """Best-fit-decreasing packing via the C++ engine.

    ``lengths``: int64 numpy array of document lengths.  Returns
    ``(row_assignment, row_offset, n_rows)`` int64 arrays, or None when the
    native library is unavailable (caller uses the Python fallback).
    Raises ValueError for invalid lengths (the engine's -1)."""
    lib = _load()
    if lib is None:
        return None
    import numpy as np

    lengths = np.ascontiguousarray(lengths, dtype=np.int64)
    n = len(lengths)
    assignment = np.empty(n, dtype=np.int64)
    offset = np.empty(n, dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    rows = lib.kfpk_pack(
        lengths.ctypes.data_as(i64p), n, int(row_len),
        assignment.ctypes.data_as(i64p), offset.ctypes.data_as(i64p),
    )
    if rows < 0:
        raise ValueError(
            f"invalid document lengths for row_len={row_len}"
        )
    return assignment, offset, int(rows)
