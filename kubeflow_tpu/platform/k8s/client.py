"""Kubernetes API client: a small native REST client.

The reference platform talks to the API server through client-go (Go) and
the ``kubernetes`` python package; neither is assumed here.  This client
speaks the REST conventions directly (JSON over HTTPS, optimistic
concurrency via resourceVersion, watch streams as chunked JSON lines) and is
the single seam the controllers/web-apps depend on — ``FakeKube``
(kubeflow_tpu.platform.testing) implements the same interface in memory for
the envtest-style suites.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Protocol, Tuple

from kubeflow_tpu.platform.k8s import errors
from kubeflow_tpu.platform.k8s.types import (
    GVK,
    Resource,
    gvk_of,
    json_default,
    meta,
    name_of,
    namespace_of,
)

WatchEvent = Tuple[str, Resource]  # ("ADDED"|"MODIFIED"|"DELETED"|"BOOKMARK", obj)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeClient(Protocol):
    """The verbs the platform uses.  All objects are unstructured dicts."""

    def get(self, gvk: GVK, name: str, namespace: Optional[str] = None) -> Resource: ...

    def list(
        self,
        gvk: GVK,
        namespace: Optional[str] = None,
        *,
        label_selector: Optional[Dict[str, str]] = None,
        field_selector: Optional[Dict[str, str]] = None,
    ) -> List[Resource]: ...

    def create(self, obj: Resource, *, dry_run: bool = False) -> Resource: ...

    def update(self, obj: Resource) -> Resource: ...

    def update_status(self, obj: Resource) -> Resource: ...

    def patch(
        self,
        gvk: GVK,
        name: str,
        patch: Any,
        namespace: Optional[str] = None,
        *,
        patch_type: str = "merge",
    ) -> Resource: ...

    def delete(
        self,
        gvk: GVK,
        name: str,
        namespace: Optional[str] = None,
        *,
        propagation: str = "Background",
    ) -> None: ...

    def watch(
        self,
        gvk: GVK,
        namespace: Optional[str] = None,
        *,
        resource_version: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[WatchEvent]: ...

    def can_i(
        self,
        user: str,
        verb: str,
        gvk: GVK,
        namespace: Optional[str] = None,
        *,
        groups: Optional[List[str]] = None,
        subresource: str = "",
    ) -> bool: ...

    def pod_logs(
        self, name: str, namespace: str, *, container: Optional[str] = None
    ) -> str: ...


def _selector_string(label_selector: Optional[Dict[str, str]]) -> Optional[str]:
    if not label_selector:
        return None
    return ",".join(f"{k}={v}" for k, v in sorted(label_selector.items()))


class TokenBucket:
    """QPS/burst rate limiter for API-server traffic (the reference exposes
    the same pair as manager flags, notebook-controller main.go:64-76).
    Thread-safe; acquire() blocks until a token is available."""

    def __init__(self, qps: float, burst: int):
        self.qps = float(qps)
        self.burst = float(max(burst, 1))
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> None:
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class RestKubeClient:
    """KubeClient over the real API server.

    Config resolution: explicit args → in-cluster service account →
    $KUBECONFIG/~/.kube/config (current-context, token or client-cert auth).

    ``qps``/``burst`` bound request rate (env ``K8S_CLIENT_QPS`` /
    ``K8S_CLIENT_BURST``; watch long-polls are exempt — they hold a
    connection, they don't spam requests).
    """

    def __init__(
        self,
        base_url: Optional[str] = None,
        *,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        client_cert: Optional[Tuple[str, str]] = None,
        verify: Optional[bool] = None,
        timeout: float = 30.0,
        qps: Optional[float] = None,
        burst: Optional[int] = None,
    ):
        import requests

        if base_url is None:
            base_url, token, ca_cert, client_cert = self._resolve_config()
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        if qps is None:
            qps = float(os.environ.get("K8S_CLIENT_QPS", "50"))
        if burst is None:
            burst = int(os.environ.get("K8S_CLIENT_BURST", "100"))
        self._limiter = TokenBucket(qps, burst) if qps > 0 else None
        self._session = requests.Session()
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        if client_cert:
            self._session.cert = client_cert
        if verify is not None:
            self._session.verify = verify
        elif ca_cert:
            self._session.verify = ca_cert

    @staticmethod
    def _resolve_config() -> Tuple[str, Optional[str], Optional[str], Optional[Tuple[str, str]]]:
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        if host and os.path.exists(f"{SERVICE_ACCOUNT_DIR}/token"):
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            with open(f"{SERVICE_ACCOUNT_DIR}/token") as f:
                token = f.read().strip()
            ca = f"{SERVICE_ACCOUNT_DIR}/ca.crt"
            return f"https://{host}:{port}", token, ca if os.path.exists(ca) else None, None
        # kubeconfig
        import yaml

        path = os.environ.get("KUBECONFIG", os.path.expanduser("~/.kube/config"))
        if not os.path.exists(path):
            raise RuntimeError(
                "no API server config: not in-cluster and no kubeconfig at " + path
            )
        with open(path) as f:
            kc = yaml.safe_load(f)
        ctx_name = kc.get("current-context")
        ctx = next(c["context"] for c in kc["contexts"] if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in kc["clusters"] if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in kc["users"] if u["name"] == ctx["user"])
        token = user.get("token")
        cert = None
        if "client-certificate" in user:
            cert = (user["client-certificate"], user["client-key"])
        ca = cluster.get("certificate-authority")
        return cluster["server"], token, ca, cert

    # -- plumbing ------------------------------------------------------------

    def _request(self, method: str, path: str, *, params: Optional[dict] = None,
                 body: Optional[Any] = None, stream: bool = False,
                 verb: Optional[str] = None, kind: str = ""):
        """``verb``/``kind`` label the client metrics (semantic verb —
        list vs get both ride HTTP GET — and the resource kind), the same
        surface the reference gets from client-go's rest_client_* series;
        the call is also a span on the current reconcile trace."""
        from kubeflow_tpu.platform.runtime import metrics, trace

        verb = verb or method.lower()
        if self._limiter is not None:
            self._limiter.acquire()
        headers = {}
        if method == "PATCH":
            ptype = (params or {}).pop("_patch_type", "merge")
            headers["Content-Type"] = {
                "merge": "application/merge-patch+json",
                "json": "application/json-patch+json",
                "strategic": "application/strategic-merge-patch+json",
                "apply": "application/apply-patch+yaml",
            }[ptype]
        data = None
        if body is not None:
            # Serialize here (not via requests' json=) so frozen cache
            # views (types.FrozenResource) cross the wire directly — a
            # read-modify-write round trip never deep-copies just to
            # serialize.
            data = json.dumps(body, default=json_default)
            headers.setdefault("Content-Type", "application/json")
        code = "<error>"
        t0 = time.perf_counter()
        try:
            with trace.span(f"k8s.{verb}", kind=kind) as sp:
                resp = self._session.request(
                    method,
                    self.base_url + path,
                    params=params,
                    data=data,
                    headers=headers or None,
                    stream=stream,
                    timeout=None if stream else self.timeout,
                )
                code = str(resp.status_code)
                if sp is not None:
                    sp.attrs["code"] = code
                if resp.status_code >= 400:
                    try:
                        status = resp.json()
                        message = status.get("message", resp.text)
                    except Exception:
                        status, message = None, resp.text
                    raise errors.error_for_status(
                        resp.status_code, message, status)
                return resp
        finally:
            metrics.rest_client_request_duration_seconds.labels(
                verb=verb, kind=kind).observe(time.perf_counter() - t0)
            metrics.rest_client_requests_total.labels(
                verb=verb, kind=kind, code=code).inc()

    # -- verbs ---------------------------------------------------------------

    def get(self, gvk: GVK, name: str, namespace: Optional[str] = None) -> Resource:
        return self._request("GET", gvk.path(namespace, name),
                             verb="get", kind=gvk.kind).json()

    def list(self, gvk, namespace=None, *, label_selector=None,
             field_selector=None) -> List[Resource]:
        """``field_selector`` is a dict of dotted field path → exact value
        (e.g. ``{"involvedObject.name": "nb"}``), serialized to the API
        server's fieldSelector syntax — only fields the server indexes for
        the kind are accepted (events, pods.spec.nodeName, metadata.*)."""
        params = {}
        sel = _selector_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        fsel = _selector_string(field_selector)
        if fsel:
            params["fieldSelector"] = fsel
        data = self._request("GET", gvk.path(namespace), params=params,
                             verb="list", kind=gvk.kind).json()
        return data.get("items", [])

    def list_with_rv(self, gvk, namespace=None):
        """List plus the collection resourceVersion — the correct point to
        resume a watch from (object RVs miss deletions; informers need the
        snapshot RV)."""
        data = self._request("GET", gvk.path(namespace),
                             verb="list", kind=gvk.kind).json()
        rv = ((data.get("metadata") or {}).get("resourceVersion"))
        return data.get("items", []), rv

    def create(self, obj: Resource, *, dry_run: bool = False) -> Resource:
        gvk = gvk_of(obj)
        params = {"dryRun": "All"} if dry_run else None
        return self._request(
            "POST", gvk.path(namespace_of(obj)), params=params, body=obj,
            verb="create", kind=gvk.kind,
        ).json()

    def update(self, obj: Resource) -> Resource:
        gvk = gvk_of(obj)
        return self._request(
            "PUT", gvk.path(namespace_of(obj), name_of(obj)), body=obj,
            verb="update", kind=gvk.kind,
        ).json()

    def update_status(self, obj: Resource) -> Resource:
        gvk = gvk_of(obj)
        path = gvk.path(namespace_of(obj), name_of(obj)) + "/status"
        return self._request("PUT", path, body=obj,
                             verb="update_status", kind=gvk.kind).json()

    def patch(self, gvk, name, patch, namespace=None, *, patch_type="merge") -> Resource:
        return self._request(
            "PATCH",
            gvk.path(namespace, name),
            params={"_patch_type": patch_type},
            body=patch,
            verb="patch", kind=gvk.kind,
        ).json()

    def delete(self, gvk, name, namespace=None, *, propagation="Background") -> None:
        self._request(
            "DELETE",
            gvk.path(namespace, name),
            body={"propagationPolicy": propagation},
            verb="delete", kind=gvk.kind,
        )

    # Watch streams are bounded server-side so a half-dead connection can't
    # freeze the controller silently: the server closes after
    # WATCH_TIMEOUT_SECONDS and the caller's watch loop re-establishes; the
    # client read timeout is slightly larger as a backstop (it fires as an
    # exception the watch loop also treats as a reconnect).
    WATCH_TIMEOUT_SECONDS = 300

    def watch(self, gvk, namespace=None, *, resource_version=None,
              label_selector=None, stop: Optional[threading.Event] = None):
        params: Dict[str, Any] = {
            "watch": "true",
            # int(): a real apiserver rejects fractional timeoutSeconds;
            # tests overriding WATCH_TIMEOUT_SECONDS with a float must not
            # bake a wire format only the fake accepts.
            "timeoutSeconds": str(max(1, int(self.WATCH_TIMEOUT_SECONDS))),
        }
        if resource_version:
            params["resourceVersion"] = resource_version
        sel = _selector_string(label_selector)
        if sel:
            params["labelSelector"] = sel
        from kubeflow_tpu.platform.runtime import metrics

        try:
            resp = self._session.request(
                "GET",
                self.base_url + gvk.path(namespace),
                params=params,
                stream=True,
                timeout=(10, self.WATCH_TIMEOUT_SECONDS + 30),
            )
        except Exception:
            metrics.rest_client_requests_total.labels(
                verb="watch", kind=gvk.kind, code="<error>").inc()
            raise
        # Establishment only — a watch holds a connection for minutes, so
        # its duration histogram would only measure the bounded window.
        metrics.rest_client_requests_total.labels(
            verb="watch", kind=gvk.kind, code=str(resp.status_code)).inc()
        if resp.status_code >= 400:
            raise errors.error_for_status(resp.status_code, resp.text)
        try:
            for line in resp.iter_lines():
                if stop is not None and stop.is_set():
                    return
                if not line:
                    continue
                evt = json.loads(line)
                yield evt.get("type", ""), evt.get("object", {})
        finally:
            resp.close()

    def pod_logs(self, name, namespace, *, container=None) -> str:
        """GET .../pods/<name>/log — the reference JWA logs endpoint's
        backing call (reference crud_backend/api/pod.py:11-15)."""
        params = {"container": container} if container else None
        path = f"/api/v1/namespaces/{namespace}/pods/{name}/log"
        return self._request("GET", path, params=params,
                             verb="logs", kind="Pod").text

    def can_i(self, user, verb, gvk, namespace=None, *, groups=None, subresource="") -> bool:
        review = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "spec": {
                "user": user,
                "groups": groups or [],
                "resourceAttributes": {
                    "group": gvk.group,
                    "resource": gvk.plural,
                    "subresource": subresource,
                    "namespace": namespace or "",
                    "verb": verb,
                },
            },
        }
        resp = self._request(
            "POST", "/apis/authorization.k8s.io/v1/subjectaccessreviews",
            body=review, verb="create", kind="SubjectAccessReview",
        ).json()
        return bool(resp.get("status", {}).get("allowed"))
